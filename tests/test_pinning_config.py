"""Pinning-strategy selection + Pinata pinner + tokenizer config knob.

Covers VERDICT r2 items 4 (ipfs.strategy reaches the node's production
path; Pinata parity with `miner/src/ipfs.ts:79-114`) and 3's wiring half
(clip_bpe tokenizer selected from ModelConfig with vocab/merges files;
golden tokenization checked against the documented OpenAI CLIP example
ids — the fixture vocab pins those words at their real CLIP ids).
"""
from __future__ import annotations

import io
import json
import os

import numpy as np
import pytest

from arbius_tpu.l0.base58 import b58encode
from arbius_tpu.l0.cid import cid_of_solution_files
from arbius_tpu.node.config import ConfigError, IpfsConfig, load_config
from arbius_tpu.node.pinners import (
    HttpDaemonPinner,
    LocalPinner,
    PinataPinner,
    PinMismatchError,
    build_pinner,
)
from arbius_tpu.node.store import ContentStore

FIXTURES = os.path.join(os.path.dirname(__file__), "fixtures")
FILES = {"out-1.png": b"\x89PNG fake" * 32}


def _body(req) -> bytes:
    """Join a request's chunked multipart body (pinners send a list of
    chunks so file bytes are referenced, not copied)."""
    d = req.data
    return d if isinstance(d, bytes) else b"".join(d)


# -- config knob -----------------------------------------------------------

def test_ipfs_config_defaults_to_local():
    cfg = load_config({})
    assert cfg.ipfs.strategy == "local"


def test_ipfs_config_validates_strategy():
    with pytest.raises(ConfigError, match="strategy"):
        load_config({"ipfs": {"strategy": "carrier-pigeon"}})
    with pytest.raises(ConfigError, match="daemon_url"):
        load_config({"ipfs": {"strategy": "http_daemon"}})
    with pytest.raises(ConfigError, match="pinata_jwt"):
        load_config({"ipfs": {"strategy": "pinata"}})


def test_tokenizer_config_validates():
    model = {"id": "0x1", "template": "anythingv3"}
    with pytest.raises(ConfigError, match="tokenizer"):
        load_config({"models": [dict(model, tokenizer="word2vec")]})
    with pytest.raises(ConfigError, match="vocab_path"):
        load_config({"models": [dict(model, tokenizer="clip_bpe")]})
    cfg = load_config({"models": [dict(
        model, tokenizer="clip_bpe",
        vocab_path="v.json", merges_path="m.txt")]})
    assert cfg.models[0].tokenizer == "clip_bpe"


def test_golden_config_validates():
    model = {"id": "0x1", "template": "anythingv3"}
    with pytest.raises(ConfigError, match="golden"):
        load_config({"models": [dict(model, golden={"seed": 1})]})
    cfg = load_config({"models": [dict(model, golden={
        "input": {"prompt": "arbius test cat"}, "seed": 1337,
        "cid": "0x1220" + "ab" * 32})]})
    assert cfg.models[0].golden["seed"] == 1337


# -- strategy factory ------------------------------------------------------

def test_build_pinner_per_strategy(tmp_path):
    store = ContentStore(str(tmp_path))
    assert isinstance(build_pinner(IpfsConfig(), store), LocalPinner)
    assert build_pinner(IpfsConfig(), None) is None
    p = build_pinner(IpfsConfig(strategy="http_daemon",
                                daemon_url="http://127.0.0.1:5001"), None)
    assert isinstance(p, HttpDaemonPinner)
    p = build_pinner(IpfsConfig(strategy="pinata", pinata_jwt="jwt"), None)
    assert isinstance(p, PinataPinner)


# -- pinata pinner ---------------------------------------------------------

def _fake_pinata_opener(responses: list, seen: list):
    def opener(req, timeout=None):
        seen.append(req)
        return io.BytesIO(json.dumps(responses.pop(0)).encode())
    return opener


def test_pinata_pinner_pins_and_verifies():
    root = cid_of_solution_files(FILES)
    seen: list = []
    pinner = PinataPinner("test-jwt", opener=_fake_pinata_opener(
        [{"IpfsHash": b58encode(root)}], seen))
    assert pinner.pin_files(FILES, taskid="0xabc") == root
    req = seen[0]
    assert req.full_url == PinataPinner.API_URL
    assert req.get_header("Authorization") == "Bearer test-jwt"
    body = _body(req).decode("latin-1")
    assert 'filename="0xabc/out-1.png"' in body
    assert '"cidVersion": 0' in body


def test_pinata_pinner_rejects_mismatched_root():
    pinner = PinataPinner("jwt", opener=_fake_pinata_opener(
        [{"IpfsHash": "QmWrongHash"}], []))
    with pytest.raises(PinMismatchError):
        pinner.pin_files(FILES)


# -- multipart body: chunked, not copied; timeout: configured --------------

def test_multipart_body_references_file_bytes():
    """The multipart body is a chunk list whose payload entries ARE the
    solution's bytes objects (no contiguous join — peak memory stays ~1×
    the output size for multi-MB videos), with an explicit
    Content-Length covering every chunk (urllib's iterable-body
    contract)."""
    files = {"out-1.mp4": b"\x00\x01" * 4096, "out-2.png": b"\x89PNG" * 64}
    for pinner, answer in ((HttpDaemonPinner("http://127.0.0.1:1"), b""),
                           (PinataPinner("jwt"), b"{}")):
        seen: list = []
        pinner.opener = lambda req, timeout=None, _a=answer: (
            seen.append(req), io.BytesIO(_a))[1]
        with pytest.raises(PinMismatchError):
            pinner.pin_files(dict(files))
        req = seen[0]
        assert not isinstance(req.data, bytes)
        chunk_ids = {id(c) for c in req.data}
        for blob in files.values():
            assert id(blob) in chunk_ids, "file bytes were copied"
        assert int(req.get_header("Content-length")) == \
            sum(len(c) for c in req.data)


def test_ipfs_timeout_threads_from_config_to_request():
    """MiningConfig.ipfs.timeout reaches every remote pin call — the
    old hard-coded 60 s is just the schema default now."""
    cfg = load_config({"ipfs": {"strategy": "http_daemon",
                                "daemon_url": "http://127.0.0.1:1",
                                "timeout": 7.5}})
    pinner = build_pinner(cfg.ipfs, None)
    assert pinner.timeout == 7.5
    seen: list = []

    def opener(req, timeout=None):
        seen.append(timeout)
        return io.BytesIO(b"")

    pinner.opener = opener
    with pytest.raises(PinMismatchError):
        pinner.pin_files(FILES)
    assert seen == [7.5]
    with pytest.raises(ConfigError, match="timeout"):
        load_config({"ipfs": {"timeout": 0}})


# -- node integration: each strategy drives _store_solution -----------------

class _EchoOpener:
    """Plays a well-behaved pinning service: recomputes the dir-wrap CID
    from the multipart body it receives, like a real daemon would."""

    def __init__(self):
        self.reqs = []

    def __call__(self, req, timeout=None):
        self.reqs.append(req)
        files = {}
        for part in _body(req).split(b"--" + PinataPinner.BOUNDARY.encode()):
            if b'name="file"' not in part:
                continue
            head, _, body = part.partition(b"\r\n\r\n")
            name = head.split(b'filename="')[1].split(b'"')[0].decode()
            files[name.split("/", 1)[-1]] = body[:-2]  # strip \r\n
        root = cid_of_solution_files(files)
        return io.BytesIO(json.dumps({"IpfsHash": b58encode(root)}).encode())


def _mine_one(tmp_path, ipfs: IpfsConfig, opener=None):
    """Drive one task through solve with the given pinning strategy."""
    from tests.test_node import build_world, drain, submit

    eng, tok, chain, node, mid = build_world(
        store_dir=str(tmp_path / "store"), ipfs=ipfs)
    if opener is not None:
        node.pinner.opener = opener
    taskid = submit(eng, mid)
    assert drain(node) >= 1
    assert eng.solutions, "no solution was committed"
    return node


def test_node_mines_with_local_strategy(tmp_path):
    node = _mine_one(tmp_path, IpfsConfig())
    assert isinstance(node.pinner, LocalPinner)
    assert node.store.stats()["files"] > 0


def test_node_mines_with_pinata_strategy(tmp_path):
    echo = _EchoOpener()
    node = _mine_one(tmp_path,
                     IpfsConfig(strategy="pinata", pinata_jwt="j"),
                     opener=echo)
    assert isinstance(node.pinner, PinataPinner)
    assert echo.reqs, "pinata endpoint was never called"
    # remote strategy still mirrors into the local store for the gateway
    assert node.store.stats()["files"] > 0


def test_node_mines_with_http_daemon_strategy(tmp_path):
    class DaemonOpener(_EchoOpener):
        def __call__(self, req, timeout=None):
            self.reqs.append(req)
            files = {}
            for part in _body(req).split(
                    b"--" + HttpDaemonPinner.BOUNDARY.encode()):
                if b'name="file"' not in part:
                    continue
                head, _, body = part.partition(b"\r\n\r\n")
                name = head.split(b'filename="')[1].split(b'"')[0].decode()
                files[name] = body[:-2]
            root = cid_of_solution_files(files)
            lines = [json.dumps({"Name": n, "Hash": "x"}) for n in files]
            lines.append(json.dumps({"Name": "", "Hash": b58encode(root)}))
            return io.BytesIO("\n".join(lines).encode())

    echo = DaemonOpener()
    node = _mine_one(
        tmp_path,
        IpfsConfig(strategy="http_daemon", daemon_url="http://127.0.0.1:1"),
        opener=echo)
    assert isinstance(node.pinner, HttpDaemonPinner)
    assert echo.reqs, "daemon endpoint was never called"


def test_pin_failure_does_not_stop_mining(tmp_path):
    def broken_opener(req, timeout=None):
        raise OSError("network down")

    node = _mine_one(tmp_path,
                     IpfsConfig(strategy="pinata", pinata_jwt="j"),
                     opener=broken_opener)
    # solution still committed (asserted in _mine_one) and mirrored locally
    assert node.store.stats()["files"] > 0


# -- clip_bpe tokenizer golden ids -----------------------------------------

def test_clip_bpe_documented_example_ids():
    """OpenAI's documented CLIP example: 'a photo of a cat' tokenizes to
    [49406, 320, 1125, 539, 320, 2368, 49407]; the fixture vocab pins
    those words at their published ids and the merges assemble them."""
    from arbius_tpu.models.sd15 import CLIPBPETokenizer

    tok = CLIPBPETokenizer.from_files(
        os.path.join(FIXTURES, "clip_vocab.json"),
        os.path.join(FIXTURES, "clip_merges.txt"))
    ids = tok.encode("a photo of a cat")
    expected = [49406, 320, 1125, 539, 320, 2368, 49407]
    assert list(ids[:7]) == expected
    assert set(ids[7:].tolist()) == {49407}
    assert ids.shape == (77,) and ids.dtype == np.int32
    # case/whitespace normalization matches CLIP's
    np.testing.assert_array_equal(
        tok.encode("  A  Photo OF a CAT "), ids)
    # 'a dog' exercises a different merge chain
    assert list(tok.encode("a dog")[:4]) == [49406, 320, 1929, 49407]


def test_factory_selects_clip_bpe_tokenizer():
    from arbius_tpu.models.sd15 import CLIPBPETokenizer
    from arbius_tpu.node.config import load_config
    from arbius_tpu.node.factory import build_registry

    cfg = load_config({"models": [{
        "id": "0x" + "11" * 32, "template": "anythingv3", "tiny": True,
        "tokenizer": "clip_bpe",
        "vocab_path": os.path.join(FIXTURES, "clip_vocab.json"),
        "merges_path": os.path.join(FIXTURES, "clip_merges.txt"),
    }]})
    reg = build_registry(cfg)
    m = reg.get("0x" + "11" * 32)
    tok = m.runner.pipeline.tokenizer
    assert isinstance(tok, CLIPBPETokenizer)
    # max_length follows the (tiny) text tower
    assert tok.max_length == m.runner.pipeline.config.text.max_length


def test_factory_wires_golden_vector():
    from arbius_tpu.node.config import load_config
    from arbius_tpu.node.factory import build_registry

    golden = {"input": {"prompt": "arbius test cat"}, "seed": 1337,
              "cid": "0x1220" + "cd" * 32}
    cfg = load_config({"models": [{
        "id": "0x" + "22" * 32, "template": "anythingv3", "tiny": True,
        "golden": golden,
    }]})
    reg = build_registry(cfg)
    m = reg.get("0x" + "22" * 32)
    assert m.golden == ({"prompt": "arbius test cat"}, 1337,
                        "0x1220" + "cd" * 32)


def test_weights_dtype_validated_and_applied():
    """weights_dtype=bfloat16 casts every floating leaf of the factory's
    params (the fp16-container trade, TPU form); bad values reject."""
    import jax.numpy as jnp
    import pytest

    from arbius_tpu.node.config import ConfigError, MiningConfig, ModelConfig
    from arbius_tpu.node.factory import build_registry

    with pytest.raises(ConfigError, match="weights_dtype"):
        ModelConfig(id="0x" + "00" * 32, template="anythingv3",
                    weights_dtype="fp8")

    mid = "0x" + "cd" * 32
    cfg = MiningConfig(models=(ModelConfig(
        id=mid, template="anythingv3", tiny=True,
        weights_dtype="bfloat16"),))
    runner = build_registry(cfg).get(mid).runner
    import jax

    leaves = jax.tree_util.tree_leaves(runner.params)
    assert all(leaf.dtype == jnp.bfloat16
               for leaf in leaves if jnp.issubdtype(leaf.dtype, jnp.inexact))


def test_bundled_example_config_validates():
    """MiningConfig.example.json (the reference ships one too) must parse
    through the schema validator — it is the operator's starting point."""
    import os

    from arbius_tpu.node.config import load_config

    path = os.path.join(os.path.dirname(__file__), "..",
                        "MiningConfig.example.json")
    cfg = load_config(open(path).read())
    assert cfg.models and cfg.models[0].template == "anythingv3"
    assert cfg.models[0].weights_dtype == "bfloat16"
    assert cfg.models[0].golden is not None
