"""CPU drive of the smoke tool's live-mine burst (tools/tpu_node_smoke.
run_live_burst) — the p50/p95 task-to-commitment measurement must be
proven on the tiny world BEFORE it ever spends a real chip claim."""
from __future__ import annotations

import sys
import time
import os

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "tools"))

from test_node import build_world


def test_burst_measures_every_task_and_claims():
    from tpu_node_smoke import run_live_burst

    eng, tok, chain, node, mid = build_world()
    notes = []
    live, latencies = run_live_burst(
        node, eng, "0x" + "01" * 20, bytes.fromhex(mid[2:]), 5,
        deadline=time.perf_counter() + 300, note=notes.append,
        task_input={"negative_prompt": ""})  # tiny world's template shape
    assert live["attempted"] and live["n_tasks"] == 5
    assert live["solved"] == 5, (live, notes)
    assert len(latencies) == 5
    assert all(x > 0 for x in latencies)
    # later submissions wait behind earlier solves: the queueing the
    # p50/p95 distribution exists to capture
    assert live["claimed"] == 5
