"""Node integration tests — the event→job→solve→commit→reveal→claim loop
against the in-process fake chain, closing the reference's biggest test gap
(SURVEY.md §4: "no miner-loop unit tests").

The model here is a fake deterministic runner (bytes derived from
input+seed) so the protocol mechanics are tested without JAX; the real
SD-1.5 runner goes through the same `solve_cid` path (covered in
test_node_sd15.py).
"""
from __future__ import annotations

import json

import pytest

from arbius_tpu.chain import Engine, TokenLedger, WAD
from arbius_tpu.l0.cid import cid_hex, cid_of_solution_files
from arbius_tpu.node import (
    AutomineConfig,
    BootError,
    LocalChain,
    MinerNode,
    MiningConfig,
    ModelConfig,
    ModelRegistry,
    RegisteredModel,
    load_config,
)
from arbius_tpu.templates.engine import load_template

MINER = "0x" + "aa" * 20
OTHER = "0x" + "bb" * 20
USER = "0x" + "01" * 20
MODEL_ADDR = "0x" + "33" * 20


def fake_runner(hydrated: dict, seed: int) -> dict:
    """Deterministic in (input, seed); output depends on both."""
    blob = json.dumps({k: v for k, v in sorted(hydrated.items())
                       if k != "seed"}).encode() + seed.to_bytes(8, "big")
    return {"out-1.png": b"\x89PNG" + blob}


def build_world(*, evilmode=False, automine=None, miner_stake=100 * WAD,
                model_fee=0, **cfg_overrides):
    tok = TokenLedger()
    eng = Engine(tok, start_time=10_000)
    tok.mint(Engine.ADDRESS, 600_000 * WAD)
    for a in (MINER, OTHER, USER):
        tok.mint(a, 1_000 * WAD)
        tok.approve(a, Engine.ADDRESS, 10**30)
    mid_bytes = eng.register_model(USER, MODEL_ADDR, model_fee,
                                   b'{"meta":{"title":"anything"}}')
    mid = "0x" + mid_bytes.hex()

    template = load_template("anythingv3")
    registry = ModelRegistry()
    registry.register(RegisteredModel(id=mid, template=template,
                                      runner=fake_runner))
    chain = LocalChain(eng, MINER)
    if miner_stake:
        chain.validator_deposit(miner_stake)
    cfg = MiningConfig(evilmode=evilmode,
                       models=(ModelConfig(id=mid, template="anythingv3"),),
                       automine=automine or AutomineConfig(),
                       **cfg_overrides)
    node = MinerNode(chain, cfg, registry)
    node.boot()
    drain(node)  # settle the boot-queued stake job (re-queues at +600s)
    return eng, tok, chain, node, mid


def task_input(prompt="a cat"):
    # negative_prompt is required=true in the template (no default fallback
    # for required fields — hydrateInput parity, models.ts:163-168)
    return {"prompt": prompt, "negative_prompt": ""}


def submit(eng, mid, prompt="a cat", fee=0, sender=USER):
    return "0x" + eng.submit_task(
        sender, 0, sender, bytes.fromhex(mid[2:]), fee,
        json.dumps(task_input(prompt)).encode()).hex()


def drain(node, n=10):
    total = 0
    for _ in range(n):
        done = node.tick()
        total += done
        if done == 0:
            break
    return total


def expected_cid(eng, taskid, mid):
    from arbius_tpu.l0.commitment import taskid2seed
    from arbius_tpu.templates.engine import hydrate_input, load_template

    raw = json.loads(eng.task_input_data[bytes.fromhex(taskid[2:])])
    hydrated = hydrate_input(raw, load_template("anythingv3"))
    hydrated["seed"] = taskid2seed(taskid)
    files = fake_runner(hydrated, hydrated["seed"])
    return cid_hex(cid_of_solution_files(files))


# -- happy path ------------------------------------------------------------

def test_task_to_solution_to_claim():
    eng, tok, chain, node, mid = build_world()
    tid = submit(eng, mid, fee=10 * WAD)
    drain(node)
    sol = eng.solutions[bytes.fromhex(tid[2:])]
    assert sol.validator == MINER
    assert "0x" + sol.cid.hex() == expected_cid(eng, tid, mid)
    assert node.metrics.solutions_submitted == 1
    # claim is time-gated
    bal0 = tok.balance_of(MINER)
    eng.advance_time(2000 + 121)
    drain(node)
    assert node.metrics.solutions_claimed == 1
    assert tok.balance_of(MINER) - bal0 == 9 * WAD  # 10 - 10% treasury cut


def test_solution_is_deterministic_per_taskid():
    eng, _, _, node, mid = build_world()
    t1 = submit(eng, mid, prompt="same prompt")
    t2 = submit(eng, mid, prompt="same prompt")
    drain(node)
    c1 = eng.solutions[bytes.fromhex(t1[2:])].cid
    c2 = eng.solutions[bytes.fromhex(t2[2:])].cid
    assert c1 != c2  # different taskid ⇒ different seed ⇒ different bytes


def test_unknown_model_ignored():
    eng, _, _, node, mid = build_world()
    other_model = eng.register_model(USER, MODEL_ADDR, 0, b"other template")
    eng.submit_task(USER, 0, USER, other_model, 0,
                    json.dumps(task_input()).encode())
    assert drain(node) == 0
    # only the re-queued stake heartbeat remains
    assert node.db.job_count() == 1


def test_min_fee_filter():
    eng, tok, chain, node, mid = build_world()
    m = node.registry.get(mid)
    node.registry.register(
        RegisteredModel(id=mid, template=m.template, runner=m.runner,
                        min_fee=5 * WAD))
    t_low = submit(eng, mid, fee=1 * WAD)
    t_ok = submit(eng, mid, fee=5 * WAD)
    drain(node)
    assert bytes.fromhex(t_low[2:]) not in eng.solutions
    assert bytes.fromhex(t_ok[2:]) in eng.solutions


def test_invalid_input_marks_task_and_contests_others_solution():
    """Garbage task input → mark invalid; when OTHER solves it anyway, the
    node contests (index.ts:236-266 flow)."""
    eng, tok, chain, node, mid = build_world()
    other_chain = LocalChain(eng, OTHER)
    other_chain.validator_deposit(100 * WAD)
    tid_b = eng.submit_task(USER, 0, USER, bytes.fromhex(mid[2:]), 0,
                            b"this is not json")
    tid = "0x" + tid_b.hex()
    drain(node)
    assert node.db.is_invalid_task(tid)
    assert tid_b not in eng.solutions
    # other miner reveals some CID for the invalid task
    bad_cid = "0x1220" + "cc" * 32
    other_chain.signal_commitment(
        other_chain.generate_commitment(tid, bad_cid))
    other_chain.submit_solution(tid, bad_cid)
    drain(node)
    assert node.metrics.contestations_submitted == 1
    con = eng.contestations[tid_b]
    assert con.validator == MINER


def test_evilmode_contested_by_honest_node():
    """Evil miner commits the sentinel-wrong CID; honest node computes the
    real one, sees the mismatch, contests, and wins the vote."""
    eng, tok, chain, evil_node, mid = build_world(evilmode=True)
    # honest node shares the same fake chain
    honest_chain = LocalChain(eng, OTHER)
    honest_chain.validator_deposit(100 * WAD)
    template = load_template("anythingv3")
    registry = ModelRegistry()
    registry.register(RegisteredModel(id=mid, template=template,
                                      runner=fake_runner))
    honest = MinerNode(honest_chain,
                       MiningConfig(models=(ModelConfig(id=mid,
                                                        template="anythingv3"),)),
                       registry)
    honest.boot()

    tid = submit(eng, mid)
    drain(evil_node)   # evil wins the race with a wrong CID
    sol = eng.solutions[bytes.fromhex(tid[2:])]
    assert sol.cid.endswith(b"\x06\x66")
    drain(honest)      # honest computes real CID, mismatches, contests
    assert honest.metrics.contestations_submitted == 1
    tid_b = bytes.fromhex(tid[2:])
    assert eng.contestations[tid_b].validator == OTHER


def test_stake_auto_topup():
    """With supply active, the stake job tops up to minimum*(1+20%)."""
    tok = TokenLedger()
    eng = Engine(tok, start_time=10_000)
    tok.mint(Engine.ADDRESS, 590_000 * WAD)   # supply 10k → minimum 8
    tok.mint(MINER, 1_000 * WAD)
    tok.approve(MINER, Engine.ADDRESS, 10**30)
    chain = LocalChain(eng, MINER)
    node = MinerNode(chain, MiningConfig(), ModelRegistry())
    node.boot()
    drain(node)
    minimum = eng.get_validator_minimum()
    staked = eng.validators[MINER].staked
    assert staked >= minimum
    assert staked == pytest.approx(minimum * 1.2, rel=0.01)
    # job re-queued itself for later
    assert node.db.job_count() == 1


def test_automine_submits_and_solves_own_tasks():
    eng, tok, chain, node, mid = build_world()
    # model id only exists after deployment, so configure automine now and
    # queue its first job (boot would have, had the config been enabled)
    node.config = MiningConfig(
        models=node.config.models,
        automine=AutomineConfig(enabled=True, model=mid, fee=0,
                                input=task_input("self work"), delay=60))
    node.db.queue_job("automine", {}, priority=10)
    drain(node)
    # one automined task got solved by ourselves
    assert node.metrics.solutions_submitted == 1
    assert node.db.job_count() >= 1  # automine re-queued at +60s
    eng.advance_time(61)
    drain(node)
    assert node.metrics.solutions_submitted == 2


def test_boot_self_test_golden():
    eng, tok, chain, node, mid = build_world()
    m = node.registry.get(mid)
    inp = task_input("arbius test cat")
    from arbius_tpu.templates.engine import hydrate_input
    hydrated = hydrate_input(dict(inp), m.template)
    good = cid_hex(cid_of_solution_files(fake_runner(hydrated, 1337)))
    node.registry.register(RegisteredModel(
        id=mid, template=m.template, runner=m.runner,
        golden=(inp, 1337, good)))
    node.boot()  # passes
    node.registry.register(RegisteredModel(
        id=mid, template=m.template, runner=m.runner,
        golden=(inp, 1337, "0x1220" + "00" * 32)))
    with pytest.raises(BootError, match="self-test"):
        node.boot()


def test_version_check_halts_boot():
    eng, tok, chain, node, mid = build_world()
    eng.set_version(99)
    with pytest.raises(BootError, match="version"):
        node.boot()


def test_failed_jobs_quarantined():
    eng, tok, chain, node, mid = build_world()

    def broken_runner(hydrated, seed):
        raise RuntimeError("model exploded")

    m = node.registry.get(mid)
    node.registry.register(RegisteredModel(id=mid, template=m.template,
                                           runner=broken_runner))
    submit(eng, mid)
    drain(node)
    failed = node.db.failed_jobs()
    assert any(m == "solve" for m, _ in failed)
    # nothing stuck in the live queue except the stake heartbeat
    assert all(j.method == "validatorStake"
               for j in node.db.get_jobs(now=10**12))


def test_config_load_validation():
    from arbius_tpu.node import ConfigError

    cfg = load_config(json.dumps({
        "db_path": ":memory:",
        "models": [{"id": "0x" + "ab" * 32, "template": "anythingv3"}],
        "automine": {"enabled": True, "delay": 30},
    }))
    assert cfg.models[0].template == "anythingv3"
    assert cfg.automine.delay == 30
    with pytest.raises(ConfigError, match="unknown config keys"):
        load_config('{"not_a_key": 1}')


def test_solve_jobs_batch_into_one_dispatch():
    """Tasks sharing a shape bucket run as ONE runner batch (the dp win
    over the reference's strictly-serial solve queue, index.ts:555-563)."""
    eng, tok, chain, node, mid = build_world()
    batches = []

    class BatchRunner:
        def __call__(self, hydrated, seed):
            return self.run_batch([(hydrated, seed)])[0]

        def run_batch(self, items):
            batches.append(len(items))
            return [fake_runner(h, s) for h, s in items]

    m = node.registry.get(mid)
    node.registry.register(RegisteredModel(id=mid, template=m.template,
                                           runner=BatchRunner()))
    node.config = MiningConfig(models=node.config.models, canonical_batch=4)
    tids = [submit(eng, mid, prompt=f"p{i}") for i in range(3)]
    drain(node)
    # one dispatch, padded to the canonical batch (3 real + 1 pad)
    assert batches == [4]
    for tid in tids:
        assert bytes.fromhex(tid[2:]) in eng.solutions


def test_claim_latency_metrics_recorded():
    eng, tok, chain, node, mid = build_world()
    submit(eng, mid)
    drain(node)
    assert len(node.metrics.solve_latency) == 1
    assert len(node.metrics.stage_seconds["infer"]) == 1
    assert len(node.metrics.stage_seconds["commit"]) == 1


def test_db_prune_keeps_unclaimed():
    eng, tok, chain, node, mid = build_world()
    t_old = submit(eng, mid, prompt="old")
    drain(node)
    eng.advance_time(2200)
    drain(node)  # claimed
    t_new = submit(eng, mid, prompt="new")
    drain(node)  # solved but NOT claimed yet
    removed = node.db.prune_before(eng.now + 10**6)
    assert removed == 1
    assert node.db.get_task(t_old) is None
    assert node.db.get_task(t_new) is not None


def test_delegated_validator_stake_seam():
    """blockchain.ts:44-67 seam: with `delegated_validator` configured,
    stake reads AND the auto-top-up deposit target the delegated address
    (validatorDeposit is anyone-may-top-up, EngineV1.sol:581-604); the
    node's own wallet pays but never accrues stake."""
    delegated = "0x" + "dd" * 20
    tok = TokenLedger()
    eng = Engine(tok, start_time=10_000)
    tok.mint(Engine.ADDRESS, 590_000 * WAD)   # supply 10k → minimum 8
    tok.mint(MINER, 1_000 * WAD)
    tok.approve(MINER, Engine.ADDRESS, 10**30)
    chain = LocalChain(eng, MINER, validator_address=delegated)
    node = MinerNode(chain, MiningConfig(delegated_validator=delegated),
                     ModelRegistry())
    import logging
    records = []
    h = logging.Handler()
    h.emit = records.append
    logging.getLogger("arbius.node").addHandler(h)
    try:
        node.boot()
    finally:
        logging.getLogger("arbius.node").removeHandler(h)
    # the solving-gate caveat must be surfaced at boot, not at first revert
    assert any("delegated_validator" in r.getMessage() for r in records)
    drain(node)
    minimum = eng.get_validator_minimum()
    assert eng.validators[delegated].staked >= minimum
    assert MINER not in eng.validators
    # facade reads report the delegated stake
    assert chain.validator_staked() == eng.validators[delegated].staked

    from arbius_tpu.node.config import ConfigError
    with pytest.raises(ConfigError, match="delegated_validator"):
        MiningConfig(delegated_validator="not-an-address")


# -- lost-response recovery (found by simnet rpc-flap) ---------------------

def _lost_response(fn):
    """Wrap a chain tx method so it LANDS but the response is lost —
    the classic flaky-endpoint failure the retry envelope then sees as
    'already done' reverts."""
    def wrapped(*args, **kwargs):
        fn(*args, **kwargs)
        raise OSError("sim: response lost after landing")
    return wrapped


def test_reveal_lost_response_still_schedules_claim():
    eng, tok, chain, node, mid = build_world()
    chain.submit_solution = _lost_response(chain.submit_solution)
    tid = submit(eng, mid, fee=10 * WAD)
    drain(node)
    sol = eng.solutions[bytes.fromhex(tid[2:])]
    assert sol.validator == MINER
    # the reveal landed even though every attempt "failed": the node must
    # recognize its own on-chain solution and keep the lifecycle going
    assert node.metrics.solutions_submitted == 1
    assert node.db.has_job("claim", {"taskid": tid})
    eng.advance_time(2000 + 121)
    drain(node)
    assert node.metrics.solutions_claimed == 1


def test_claim_lost_response_still_counts():
    eng, tok, chain, node, mid = build_world()
    tid = submit(eng, mid, fee=10 * WAD)
    drain(node)
    chain.claim_solution = _lost_response(chain.claim_solution)
    eng.advance_time(2000 + 121)
    drain(node)
    assert eng.solutions[bytes.fromhex(tid[2:])].claimed
    assert node.metrics.solutions_claimed == 1
    # nothing quarantined: the exhausted retries resolved to success
    assert node.db.failed_jobs() == []


def test_reveal_never_landing_quarantines_visibly():
    eng, tok, chain, node, mid = build_world()

    def down(*a, **k):
        raise OSError("sim: endpoint down")

    chain.submit_solution = down
    tid = submit(eng, mid)
    drain(node)
    # no silent drop: the solve job must land in failed_jobs (task
    # conservation — simnet SIM101)
    assert ("solve" in {m for m, d in node.db.failed_jobs()
                        if d.get("taskid") == tid})
    assert bytes.fromhex(tid[2:]) not in eng.solutions


def test_stake_heartbeat_survives_chain_fault():
    eng, tok, chain, node, mid = build_world()
    orig = chain.validator_staked

    def down():
        raise OSError("sim: endpoint down")

    chain.validator_staked = down
    eng.advance_time(700)
    drain(node)
    # the job failed and was quarantined...
    assert any(m == "validatorStake" for m, _ in node.db.failed_jobs())
    # ...but the heartbeat re-queued itself (a dead stake loop would
    # eventually deregister the validator — found by simnet rpc-flap)
    assert node.db.has_job("validatorStake", {})
    chain.validator_staked = orig
    eng.advance_time(700)
    drain(node)


# -- attention-impl boot gate (ISSUE satellite: ops/flash.py) --------------

def test_boot_gates_nondefault_attention_impl():
    from arbius_tpu.ops import flash

    eng, tok, chain, node, mid = build_world()
    m = node.registry.get(mid)
    node.registry.register(RegisteredModel(
        id=mid, template=m.template, runner=m.runner,
        golden=({"prompt": "g", "negative_prompt": ""}, 1,
                "0x1220" + "00" * 32)))
    prior = flash.set_attention_impl("einsum")
    try:
        # a non-default reduction order may only mine if the self-test
        # proves the goldens still hold — skipping it must fail the boot
        with pytest.raises(BootError, match="ARBIUS_ATTN_IMPL"):
            node.boot(skip_self_test=True)
    finally:
        flash.set_attention_impl(prior)
    assert flash.attention_impl() == prior


def test_get_jobs_orders_priority_desc_then_id_asc():
    """The fleet reclaim path leans on this ordering (docs/fleet.md):
    priority DESC, insertion id ASC on ties — a re-queued job never
    jumps ahead of an older sibling at the same priority."""
    from arbius_tpu.node import NodeDB

    db = NodeDB(":memory:")
    ids = [db.queue_job("a", {"n": i}) for i in range(3)]          # prio 0
    hi = db.queue_job("hot", {}, priority=50)
    mid = db.queue_job("warm", {}, priority=10)
    jobs = db.get_jobs(now=0)
    assert [j.id for j in jobs] == [hi, mid] + ids
    # ties keep insertion order even after interleaved deletes
    db.delete_job(ids[1])
    assert [j.data.get("n") for j in db.get_jobs(now=0)
            if j.method == "a"] == [0, 2]
    db.close()


def test_get_jobs_limit_boundary_exactly_hit():
    from arbius_tpu.node import NodeDB

    db = NodeDB(":memory:")
    for i in range(101):
        db.queue_job("a", {"n": i})
    assert len(db.get_jobs(now=0)) == 100          # default limit
    assert len(db.get_jobs(now=0, limit=101)) == 101
    assert len(db.get_jobs(now=0, limit=1)) == 1
    db.close()


def test_get_jobs_excludes_future_waituntil():
    from arbius_tpu.node import NodeDB

    db = NodeDB(":memory:")
    due = db.queue_job("now", {}, waituntil=100)
    edge = db.queue_job("edge", {}, waituntil=200)
    db.queue_job("later", {}, waituntil=201)
    assert [j.id for j in db.get_jobs(now=100)] == [due]
    # waituntil == now is DUE (<=), one second later is not
    assert [j.id for j in db.get_jobs(now=200)] == [due, edge]
    db.close()
