"""graphlint — trace-spec registry, GRAPH4xx rules, canonical
fingerprints, and the tier-1 golden gate over `goldens/graph/`.

The self-check here is the actual guardrail: every registered pipeline
entry point is re-traced on CPU and compared against the checked-in
golden fingerprints — change a traced XLA program (dtype, reduction,
callback, schedule table) and THIS file goes red with a structural
diff. The perturbation tests prove the gate fails closed rather than
assuming it.
"""
import json
import math
import os
import pathlib
import subprocess
import sys

import pytest

REPO = pathlib.Path(__file__).resolve().parent.parent
GOLDENS_DIR = str(REPO / "goldens" / "graph")

sys.path.insert(0, str(REPO / "tools"))

import jax
import jax.numpy as jnp

from arbius_tpu.analysis.graph import (
    audit,
    canonical_lines,
    diff_summaries,
    fingerprint,
    run_rules,
    summarize,
    trace_spec,
)
from arbius_tpu.analysis.graph import goldens as goldens_mod
from arbius_tpu.analysis.graph.cli import main as cli_main
from arbius_tpu.analysis.graph.trace import TracedProgram
from arbius_tpu.models import TraceSpec, all_trace_specs, validate_specs


def synthetic_spec(fn, args, *, entry="fn", allow=()) -> TraceSpec:
    return TraceSpec(model="synthetic", entry=entry, bucket="b1",
                     mesh="single", dtype="float32",
                     build=lambda: (fn, args), allow=allow)


def traced(fn, args, **kw) -> TracedProgram:
    return trace_spec(synthetic_spec(fn, args, **kw))


def rules_of(findings):
    return [f.rule for f in findings]


# -- registry ---------------------------------------------------------------

def test_registry_covers_every_pipeline_family():
    specs = all_trace_specs()
    models = {s.model for s in specs}
    assert {"anythingv3", "kandinsky2", "robust_video_matting",
            "zeroscopev2xl"} <= models
    # the identity axes the ISSUE names: dtype variants, mesh variants
    assert {s.dtype for s in specs} >= {"bfloat16", "float32"}
    assert any(s.mesh != "single" for s in specs), \
        "a dp/sp/tp shard_map layout must be fingerprinted"
    assert len({s.key for s in specs}) == len(specs)


def test_registry_validation_rejects_bad_specs():
    ok = synthetic_spec(lambda x: x, (jnp.float32(0),))
    with pytest.raises(ValueError, match="duplicate"):
        validate_specs([ok, ok])
    with pytest.raises(ValueError, match="filename-safe"):
        validate_specs([TraceSpec(model="Bad/Name", entry="e", bucket="b",
                                  mesh="single", dtype="float32",
                                  build=lambda: None)])
    with pytest.raises(ValueError, match="reason"):
        validate_specs([TraceSpec(model="m", entry="e", bucket="b",
                                  mesh="single", dtype="float32",
                                  build=lambda: None,
                                  allow=(("GRAPH401", ""),))])


# -- the tier-1 self-check (the actual guardrail) ---------------------------

@pytest.fixture(scope="session")
def full_audit_findings():
    return audit(goldens_dir=GOLDENS_DIR)


def test_package_self_check_clean_against_goldens(full_audit_findings):
    assert full_audit_findings == [], (
        "graphlint found rule findings or golden fingerprint drift — "
        "fix the graph change, or (if it is an intended program change) "
        "run tools/graphlint.py --golden-update and justify the diff "
        "per goldens/graph/README.md:\n"
        + "\n".join(f.text() for f in full_audit_findings))


def test_goldens_dir_matches_registry_exactly():
    keys = {s.key for s in all_trace_specs()}
    assert set(goldens_mod.recorded_keys(GOLDENS_DIR)) == keys


# -- fingerprint stability & canonicalization -------------------------------

def test_fingerprint_byte_identical_rerun():
    spec = next(s for s in all_trace_specs()
                if s.model == "robust_video_matting")
    a = trace_spec(spec)
    b = trace_spec(spec)
    assert fingerprint(a.closed) == fingerprint(b.closed)
    assert list(canonical_lines(a.closed)) == list(canonical_lines(b.closed))
    assert summarize(a.closed) == summarize(b.closed)


def test_canonicalization_ignores_names_and_metadata():
    # alpha-equivalent programs spelled with different python
    # identifiers AND different jit names: the raw jaxpr text differs
    # (the pjit `name=` metadata), the canonical fingerprint must not
    def helper_one(a, b):
        c = a + b
        return c * a

    def completely_different_name(x, y):
        t = x + y
        return t * x

    args = (jax.ShapeDtypeStruct((4,), jnp.float32),
            jax.ShapeDtypeStruct((4,), jnp.float32))
    ja = jax.make_jaxpr(jax.jit(helper_one))(*args)
    jb = jax.make_jaxpr(jax.jit(completely_different_name))(*args)
    assert str(ja) != str(jb), "test is vacuous: texts already identical"
    assert fingerprint(ja) == fingerprint(jb)


def test_canonicalization_keeps_argument_order_identity():
    # NOT alpha-equivalent: the sub-program consumes its operands in a
    # different order — a canonicalizer that renames vars without
    # emitting binder order would merge these
    def f(a, b):
        return jax.jit(lambda x, y: x - y)(a, b)

    def g(a, b):
        return jax.jit(lambda x, y: x - y)(b, a)

    args = (jax.ShapeDtypeStruct((4,), jnp.float32),
            jax.ShapeDtypeStruct((4,), jnp.float32))
    assert fingerprint(jax.make_jaxpr(f)(*args)) != \
        fingerprint(jax.make_jaxpr(g)(*args))


def test_fingerprint_sees_constant_values():
    # same graph shape, different baked-in table (a "sampler schedule
    # edit"): op histograms match, fingerprints must not
    table1 = jnp.arange(8, dtype=jnp.float32)
    table2 = jnp.arange(8, dtype=jnp.float32) * 2.0

    def use(table):
        return lambda i: table[i] + 1.0

    arg = (jax.ShapeDtypeStruct((), jnp.int32),)
    ja = jax.make_jaxpr(use(table1))(*arg)
    jb = jax.make_jaxpr(use(table2))(*arg)
    assert summarize(ja)["primitives"] == summarize(jb)["primitives"]
    assert fingerprint(ja) != fingerprint(jb)
    assert "constants" in " ".join(
        diff_summaries(summarize(ja), summarize(jb)))


# -- GRAPH4xx rules ---------------------------------------------------------

def test_graph401_host_callback():
    def noisy(x):
        jax.debug.print("x = {x}", x=x)
        return x * 2

    prog = traced(noisy, (jax.ShapeDtypeStruct((4,), jnp.float32),))
    hits = run_rules(prog)
    assert rules_of(hits) == ["GRAPH401"]
    assert "debug_callback" in hits[0].message

    clean = traced(lambda x: x * 2,
                   (jax.ShapeDtypeStruct((4,), jnp.float32),))
    assert not run_rules(clean)


def test_graph402_scatter_add_unique_indices():
    def nonunique(x, idx, upd):
        return x.at[idx].add(upd)

    def unique(x, idx, upd):
        return x.at[idx].add(upd, unique_indices=True)

    args = (jax.ShapeDtypeStruct((8,), jnp.float32),
            jax.ShapeDtypeStruct((4,), jnp.int32),
            jax.ShapeDtypeStruct((4,), jnp.float32))
    assert rules_of(run_rules(traced(nonunique, args))) == ["GRAPH402"]
    assert not run_rules(traced(unique, args))


def test_graph403_named_axis_reduction_order():
    from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P

    from arbius_tpu.parallel import MeshSpec, abstract_mesh

    mesh = abstract_mesh(MeshSpec(dp=2, sp=1, tp=2))

    def make(axes):
        return shard_map(lambda x: jax.lax.psum(x, axes), mesh=mesh,
                         in_specs=P("dp"), out_specs=P(),
                         check_rep=False)

    args = (jax.ShapeDtypeStruct((8, 4), jnp.float32),)
    bad = run_rules(traced(make(("tp", "dp")), args))
    assert rules_of(bad) == ["GRAPH403"]
    assert "canonical" in bad[0].message
    assert not run_rules(traced(make(("dp", "tp")), args))


def test_graph404_float64_in_graph():
    from jax.experimental import enable_x64

    with enable_x64():
        def drift(x):
            return jnp.sum(x.astype(jnp.float64))

        prog = traced(drift, (jax.ShapeDtypeStruct((4,), jnp.float32),))
    hits = run_rules(prog)
    assert "GRAPH404" in rules_of(hits)
    assert all(f.severity == "error" for f in hits
               if f.rule == "GRAPH404")


def test_graph405_bf16_accumulation():
    def lost_upcast(x):
        # a raw lax.reduce with an add combiner in bf16 — the exact
        # accumulation jnp.sum would have auto-upcast to f32
        return jax.lax.reduce(x, jnp.zeros((), x.dtype), jax.lax.add,
                              (0,))

    def bf16_min(x):
        # min/max combiners are exact in any order: not flagged
        return jax.lax.reduce(x, jnp.full((), jnp.inf, x.dtype),
                              jax.lax.min, (0,))

    args = (jax.ShapeDtypeStruct((16,), jnp.bfloat16),)
    hits = run_rules(traced(lost_upcast, args))
    assert rules_of(hits) == ["GRAPH405"]
    assert "bfloat16" in hits[0].message
    assert not run_rules(traced(bf16_min, args))
    # jnp.sum over bf16 is auto-upcast by jax itself — must NOT fire
    assert not run_rules(traced(
        lambda x: jnp.sum(x), args))


def test_graph407_int8_dot_must_accumulate_int32():
    def narrow(qx, qw):
        # default promotion: int8 @ int8 accumulates in int8 — wraps
        return jax.lax.dot_general(qx, qw, (((1,), (0,)), ((), ())))

    def wide(qx, qw):
        return jax.lax.dot_general(qx, qw, (((1,), (0,)), ((), ())),
                                   preferred_element_type=jnp.int32)

    args = (jax.ShapeDtypeStruct((4, 8), jnp.int8),
            jax.ShapeDtypeStruct((8, 4), jnp.int8))
    hits = run_rules(traced(narrow, args))
    assert rules_of(hits) == ["GRAPH407"]
    assert "int32" in hits[0].message
    assert not run_rules(traced(wide, args))


def test_graph407_fp8_dot_must_accumulate_f32():
    def narrow(qx, qw):
        # fp8 contraction accumulating in bf16 — the sub-f32 wobble
        # GRAPH405 polices, one notch lower
        return jax.lax.dot_general(
            qx, qw, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.bfloat16)

    def wide(qx, qw):
        return jax.lax.dot_general(
            qx, qw, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    args = (jax.ShapeDtypeStruct((4, 8), jnp.float8_e4m3fn),
            jax.ShapeDtypeStruct((8, 4), jnp.float8_e4m3fn))
    hits = run_rules(traced(narrow, args))
    assert rules_of(hits) == ["GRAPH407"]
    assert "float32" in hits[0].message
    assert not run_rules(traced(wide, args))


def test_graph407_dequant_must_pass_through_f32():
    def direct(qv, qs):
        # int8 → bf16 directly: rounds twice, backend-fusion dependent
        return qv.astype(jnp.bfloat16) * qs.astype(jnp.bfloat16)

    def via_f32(qv, qs):
        return (qv.astype(jnp.float32) * qs).astype(jnp.bfloat16)

    args = (jax.ShapeDtypeStruct((8, 8), jnp.int8),
            jax.ShapeDtypeStruct((8,), jnp.float32))
    hits = run_rules(traced(direct, args))
    assert "GRAPH407" in rules_of(hits)
    assert "float32" in hits[0].message
    assert not run_rules(traced(via_f32, args))
    # uint8 image bytes → f32 is the codec path and must stay clean
    assert not run_rules(traced(
        lambda x: x.astype(jnp.float32) / 255.0,
        (jax.ShapeDtypeStruct((8, 8, 3), jnp.uint8),)))


def test_graph407_quantized_dot_primitive_is_clean_and_waivable():
    """quant.quantized_dot ships the accumulation contract the rule
    pins (int32 accum, f32 dequant) — and the waiver machinery treats
    GRAPH407 exactly like GRAPH405 (spec-level, reason-mandatory)."""
    from arbius_tpu.quant import quantized_dot

    def qdot(qx, qw, sx, sw):
        return quantized_dot(qx, qw, sx, sw, "int8")

    args = (jax.ShapeDtypeStruct((4, 8), jnp.int8),
            jax.ShapeDtypeStruct((8, 4), jnp.int8),
            jax.ShapeDtypeStruct((4,), jnp.float32),
            jax.ShapeDtypeStruct((4,), jnp.float32))
    assert not run_rules(traced(qdot, args))

    def narrow(qx, qw):
        return jax.lax.dot_general(qx, qw, (((1,), (0,)), ((), ())))

    bad_args = (jax.ShapeDtypeStruct((4, 8), jnp.int8),
                jax.ShapeDtypeStruct((8, 4), jnp.int8))
    waived = traced(narrow, bad_args,
                    allow=(("GRAPH407", "fixture: wrap-around is the "
                            "point of this test program"),))
    assert not run_rules(waived)
    # --select machinery: GRAPH407 runs (or not) like any GRAPH4xx rule
    prog = traced(narrow, bad_args)
    assert rules_of(run_rules(prog, select={"GRAPH407"})) == ["GRAPH407"]
    assert run_rules(prog, select={"GRAPH405"}) == []


def test_graph407_quantized_probe_programs_are_clean():
    """The shipped quantized programs (probe int8 specs) hold the
    accumulation/dequant contract — the per-mode goldens pin programs
    GRAPH407 passes."""
    from arbius_tpu.parallel import meshsolve

    for spec in meshsolve.trace_specs():
        if spec.dtype != "int8":
            continue
        assert not run_rules(trace_spec(spec)), spec.key


def test_graph406_constant_prng_seed():
    def watermark(x):
        key = jax.random.PRNGKey(42)
        return x + jax.random.normal(key, x.shape)

    def threaded(x, seed):
        key = jax.random.PRNGKey(seed)
        return x + jax.random.normal(key, x.shape)

    xs = jax.ShapeDtypeStruct((4,), jnp.float32)
    hits = run_rules(traced(watermark, (xs,)))
    assert rules_of(hits) == ["GRAPH406"]
    assert "42" in hits[0].message
    assert not run_rules(traced(
        threaded, (xs, jax.ShapeDtypeStruct((), jnp.uint32))))


def test_graph406_closed_over_constant_seed():
    # a seed closed over from module scope traces as a CONSTVAR, not a
    # literal — the rule must follow const-derivation, not just inline
    # literals
    seed = jnp.uint32(1337)

    def watermark(x):
        key = jax.random.PRNGKey(seed)
        return x + jax.random.normal(key, x.shape)

    hits = run_rules(traced(watermark,
                            (jax.ShapeDtypeStruct((4,), jnp.float32),)))
    assert rules_of(hits) == ["GRAPH406"]
    assert "const" in hits[0].message


def test_graph405_checks_every_reduction_operand():
    # tuple psum: the bf16 half of a mixed (f32, bf16) reduction must
    # not hide behind the f32 first operand
    from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P

    from arbius_tpu.parallel import MeshSpec, abstract_mesh

    mesh = abstract_mesh(MeshSpec(dp=2, sp=1, tp=1))
    f = shard_map(lambda a, b: jax.lax.psum((a, b), "dp"), mesh=mesh,
                  in_specs=(P("dp"), P("dp")), out_specs=(P(), P()),
                  check_rep=False)
    hits = run_rules(traced(f, (jax.ShapeDtypeStruct((8,), jnp.float32),
                                jax.ShapeDtypeStruct((8,), jnp.bfloat16))))
    assert "GRAPH405" in rules_of(hits)


def test_spec_waiver_mirrors_pragma_semantics():
    def noisy(x):
        jax.debug.print("x = {x}", x=x)
        return x * 2

    args = (jax.ShapeDtypeStruct((4,), jnp.float32),)
    waived = traced(noisy, args,
                    allow=(("GRAPH401", "debug build diagnostic"),))
    assert not run_rules(waived)
    # waiving one rule must not waive others
    assert rules_of(run_rules(traced(noisy, args,
                                     allow=(("GRAPH402", "x"),)))) == \
        ["GRAPH401"]


def test_finding_anchors_to_canonical_eqn():
    def noisy(x):
        jax.debug.print("x = {x}", x=x)
        return x * 2

    prog = traced(noisy, (jax.ShapeDtypeStruct((4,), jnp.float32),))
    hit = run_rules(prog)[0]
    lines = list(canonical_lines(prog.closed))
    assert any(line.startswith(f"{hit.line}: ") and "callback" in line
               for line in lines), \
        "finding line must index into the canonical text"


# -- the golden gate fails closed -------------------------------------------

@pytest.fixture()
def bf16_groupnorm(monkeypatch):
    """Flip every GroupNorm in the SD-1.5 stack to ACTIVATION-dtype
    statistics — the exact regression the gate exists for."""
    import flax.linen as nn

    from arbius_tpu.models import common as common_mod
    from arbius_tpu.models.sd15 import unet as unet_mod
    from arbius_tpu.models.sd15 import vae as vae_mod

    class Bf16StatsGN(nn.Module):
        num_groups: int = 32
        epsilon: float = 1e-5

        @nn.compact
        def __call__(self, x):
            g = math.gcd(x.shape[-1], self.num_groups)
            b, h, w, c = x.shape
            xg = x.reshape(b, h, w, g, c // g)
            n = h * w * (c // g)
            zero = jnp.zeros((), x.dtype)
            s = jax.lax.reduce(xg, zero, jax.lax.add, (1, 2, 4))
            mean = (s / n)[:, None, None, :, None]
            s2 = jax.lax.reduce(xg * xg, zero, jax.lax.add, (1, 2, 4))
            var = (s2 / n)[:, None, None, :, None] - mean * mean
            out = (xg - mean) * jax.lax.rsqrt(var + self.epsilon)
            return out.reshape(b, h, w, c)

    for mod in (common_mod, unet_mod, vae_mod):
        monkeypatch.setattr(mod, "GroupNorm32", Bf16StatsGN)
    return Bf16StatsGN


def test_injected_bf16_groupnorm_fails_the_gate(bf16_groupnorm):
    """ISSUE acceptance: an intentionally perturbed graph (GroupNorm
    statistics flipped to bf16) must (a) trip GRAPH405 and (b) mismatch
    the golden fingerprint with a readable structural diff."""
    spec = next(s for s in all_trace_specs()
                if s.model == "anythingv3" and s.dtype == "bfloat16"
                and "ddim" in s.bucket)
    prog = trace_spec(spec)

    hits = run_rules(prog)
    assert "GRAPH405" in rules_of(hits), \
        "bf16 statistics must trip the low-precision accumulation rule"

    gate = goldens_mod.check([prog], GOLDENS_DIR, all_keys_expected=False)
    assert rules_of(gate) == ["GRAPH490"]
    msg = gate[0].message
    assert "reduce[bfloat16]" in msg, \
        f"mismatch message must carry the structural diff, got: {msg}"
    assert gate[0].enforced, "golden-gate findings are never waivable"


def test_golden_docs_are_deterministic(tmp_path):
    prog = traced(lambda x: x * 2 + 1,
                  (jax.ShapeDtypeStruct((4,), jnp.float32),))
    d = str(tmp_path)
    path1 = goldens_mod.write_golden(d, goldens_mod.golden_doc(prog))
    first = pathlib.Path(path1).read_bytes()
    goldens_mod.write_golden(d, goldens_mod.golden_doc(prog))
    assert pathlib.Path(path1).read_bytes() == first
    assert not goldens_mod.check([prog], d)


def test_golden_gate_missing_and_stale(tmp_path):
    d = str(tmp_path)
    prog = traced(lambda x: x * 2,
                  (jax.ShapeDtypeStruct((4,), jnp.float32),))
    # no golden recorded: fail closed
    assert rules_of(goldens_mod.check([prog], d)) == ["GRAPH491"]
    goldens_mod.update([prog], d)
    assert not goldens_mod.check([prog], d)
    # a golden whose spec vanished: stale, also fatal on full runs
    other = traced(lambda x: x + 1,
                   (jax.ShapeDtypeStruct((4,), jnp.float32),),
                   entry="gone")
    goldens_mod.write_golden(d, goldens_mod.golden_doc(other))
    assert rules_of(goldens_mod.check([prog], d)) == ["GRAPH492"]
    # ...but expected on --spec-filtered runs
    assert not goldens_mod.check([prog], d, all_keys_expected=False)
    # full update prunes the stale file; partial update must not
    goldens_mod.update([prog], d)
    assert rules_of(goldens_mod.check([prog], d)) == []
    goldens_mod.write_golden(d, goldens_mod.golden_doc(other))
    goldens_mod.update([prog], d, prune=False)
    assert set(goldens_mod.recorded_keys(d)) == \
        {prog.spec.key, other.spec.key}


def test_malformed_golden_is_usage_error(tmp_path):
    from arbius_tpu.analysis.core import AnalysisError

    prog = traced(lambda x: x * 2,
                  (jax.ShapeDtypeStruct((4,), jnp.float32),))
    path = goldens_mod.golden_path(str(tmp_path), prog.spec.key)
    pathlib.Path(path).write_text(json.dumps({"version": 99}))
    with pytest.raises(AnalysisError, match="malformed"):
        goldens_mod.check([prog], str(tmp_path))


# -- CLI + tools layer ------------------------------------------------------

def test_cli_exit_codes(tmp_path, capsys):
    assert cli_main(["--list"]) == 0
    assert cli_main(["--spec", "no-such-spec"]) == 2
    assert cli_main(["--select", "NOPE", "--spec", "x"]) == 2
    assert cli_main(["--help"]) == 0
    capsys.readouterr()


def test_cli_spec_filtered_run_and_update(tmp_path, capsys):
    d = str(tmp_path / "g")
    # empty goldens dir → missing-golden finding → exit 1
    assert cli_main(["--spec", "robust_video_matting",
                     "--goldens", d]) == 1
    out = capsys.readouterr()
    assert "GRAPH491" in out.out
    # record, then clean
    assert cli_main(["--spec", "robust_video_matting", "--goldens", d,
                     "--golden-update"]) == 0
    assert cli_main(["--spec", "robust_video_matting",
                     "--goldens", d]) == 0
    # JSON shape matches the detlint document
    assert cli_main(["--spec", "robust_video_matting", "--goldens",
                     str(tmp_path / "empty"), "--json"]) == 1
    doc = json.loads(capsys.readouterr().out)
    assert doc["version"] == 1
    assert [f["rule"] for f in doc["findings"]] == ["GRAPH491"]
    # --golden-update honors --json too (clean update → empty document)
    assert cli_main(["--spec", "robust_video_matting", "--goldens", d,
                     "--golden-update", "--json"]) == 0
    doc = json.loads(capsys.readouterr().out)
    assert doc == {"version": 1, "findings": []}


def test_audit_subset_does_not_flag_other_goldens_stale():
    from arbius_tpu.analysis.graph import audit

    spec = next(s for s in all_trace_specs()
                if s.model == "robust_video_matting")
    assert audit([spec], goldens_dir=GOLDENS_DIR) == []


def test_tools_graphlint_shares_lint_main(capsys):
    import graphlint as graphlint_tool

    assert graphlint_tool.main(["--list"]) == 0
    out = capsys.readouterr().out
    assert "robust_video_matting" in out


def test_module_entrypoint_runs():
    env = dict(os.environ, PYTHONPATH=str(REPO), JAX_PLATFORMS="cpu")
    out = subprocess.run(
        [sys.executable, "-m", "arbius_tpu.analysis.graph",
         "--spec", "robust_video_matting", "--goldens", GOLDENS_DIR],
        capture_output=True, text=True, env=env, cwd=str(REPO))
    assert out.returncode == 0, out.stdout + out.stderr


# -- obs integration --------------------------------------------------------

def test_obs_reports_graphlint_health(tmp_path):
    from arbius_tpu.obs import Obs, use_obs

    def noisy(x):
        jax.debug.print("x = {x}", x=x)
        return x * 2

    obs = Obs()
    with use_obs(obs):
        audit([synthetic_spec(noisy,
                              (jax.ShapeDtypeStruct((4,), jnp.float32),))],
              goldens_dir=str(tmp_path))
    reg = obs.registry
    assert reg.counter("arbius_graphlint_specs_traced_total").value() == 1
    assert reg.counter("arbius_graphlint_findings_total",
                       labelnames=("rule",)).value(rule="GRAPH401") == 1
    # missing golden counts as a fingerprint-gate failure
    assert reg.counter(
        "arbius_graphlint_fingerprint_mismatch_total").value() == 1
    hist = reg.get("arbius_graphlint_trace_seconds")
    assert hist is not None and hist.count() == 1
    render = reg.render()
    assert "arbius_graphlint_specs_traced_total 1" in render
