"""SD-1.5 family tests (tiny configs on the CPU mesh platform)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from arbius_tpu.models.sd15 import (
    ByteTokenizer,
    SD15Config,
    SD15Pipeline,
    TextEncoder,
    TextEncoderConfig,
    UNet2DCondition,
    UNetConfig,
    VAEConfig,
    VAEDecoder,
)

pytestmark = [pytest.mark.slow, pytest.mark.model]


class TestTokenizer:
    def test_shape_and_specials(self):
        tok = ByteTokenizer()
        ids = tok.encode("hello")
        assert ids.shape == (77,)
        assert ids[0] == 49406 and ids[6] == 49407
        assert (ids[7:] == 49407).all()

    def test_truncation(self):
        tok = ByteTokenizer()
        ids = tok.encode("x" * 500)
        assert ids.shape == (77,)
        assert ids[-1] == 49407

    def test_deterministic_and_distinct(self):
        tok = ByteTokenizer()
        assert (tok.encode("a cat") == tok.encode("a cat")).all()
        assert not (tok.encode("a cat") == tok.encode("a dog")).all()

    def test_batch(self):
        tok = ByteTokenizer()
        batch = tok.encode_batch(["a", "bb"])
        assert batch.shape == (2, 77)


class TestCLIPBPE:
    @pytest.fixture()
    def tok(self, tmp_path):
        from arbius_tpu.models.sd15 import CLIPBPETokenizer
        # tiny CLIP-style vocab: byte-unicode chars, merged pieces, </w> forms
        vocab = {"<|startoftext|>": 0, "<|endoftext|>": 1,
                 "a": 2, "c": 3, "t": 4, ".": 5,
                 "a</w>": 6, "c</w>": 7, "t</w>": 8, ".</w>": 9,
                 "ca": 10, "cat</w>": 11, "at</w>": 12}
        merges = [("c", "a"), ("ca", "t</w>"), ("a", "t</w>")]
        import json
        vp, mp = tmp_path / "vocab.json", tmp_path / "merges.txt"
        vp.write_text(json.dumps(vocab))
        mp.write_text("#version\n" + "\n".join(" ".join(m) for m in merges))
        return CLIPBPETokenizer.from_files(str(vp), str(mp))

    def test_merge_ranking(self, tok):
        ids = tok.encode("cat")
        # c+a -> ca (rank 0), ca+t</w> -> cat</w> (rank 1)
        assert list(ids[:3]) == [0, 11, 1]

    def test_punctuation_split(self, tok):
        # "cat." must split into cat + . (regex pre-tokenization), producing
        # cat</w> then .</w> — not an unknown "cat.</w>" piece
        ids = tok.encode("cat.")
        assert list(ids[:4]) == [0, 11, 9, 1]

    def test_unmerged_word_falls_to_chars(self, tok):
        ids = tok.encode("tca")
        # no merges apply except none for t,c,a order: t, c, a</w>
        assert list(ids[:5]) == [0, 4, 3, 6, 1]

    def test_lowercase_and_whitespace(self, tok):
        assert (tok.encode("  CAT  ") == tok.encode("cat")).all()

    def test_pad_and_truncate(self, tok):
        ids = tok.encode("cat " * 200)
        assert ids.shape == (77,)
        assert ids[-1] == 1


class TestModules:
    def test_unet_shapes(self):
        cfg = UNetConfig.tiny()
        unet = UNet2DCondition(cfg)
        x = jnp.zeros((2, 16, 16, 4))
        t = jnp.zeros((2,))
        ctx = jnp.zeros((2, 16, cfg.context_dim))
        params = unet.init(jax.random.PRNGKey(0), x, t, ctx)["params"]
        out = unet.apply({"params": params}, x, t, ctx)
        assert out.shape == (2, 16, 16, 4)
        assert out.dtype == jnp.float32

    def test_unet_asymmetric_hw(self):
        cfg = UNetConfig.tiny()
        unet = UNet2DCondition(cfg)
        x = jnp.zeros((1, 8, 16, 4))
        params = unet.init(jax.random.PRNGKey(0), x, jnp.zeros((1,)),
                           jnp.zeros((1, 16, cfg.context_dim)))["params"]
        out = unet.apply({"params": params}, x, jnp.zeros((1,)),
                         jnp.zeros((1, 16, cfg.context_dim)))
        assert out.shape == (1, 8, 16, 4)

    def test_vae_decoder_upsamples_8x(self):
        cfg = VAEConfig.tiny()
        vae = VAEDecoder(cfg)
        z = jnp.zeros((1, 8, 8, 4))
        params = vae.init(jax.random.PRNGKey(0), z)["params"]
        out = vae.apply({"params": params}, z)
        assert out.shape == (1, 64, 64, 3)

    def test_text_encoder_causal(self):
        cfg = TextEncoderConfig.tiny()
        enc = TextEncoder(cfg)
        ids = jnp.zeros((2, cfg.max_length), jnp.int32)
        params = enc.init(jax.random.PRNGKey(0), ids)["params"]
        base = enc.apply({"params": params}, ids)
        assert base.shape == (2, cfg.max_length, cfg.width)
        # causality: changing a later token must not affect earlier positions
        ids2 = ids.at[:, -1].set(5)
        out2 = enc.apply({"params": params}, ids2)
        np.testing.assert_allclose(np.asarray(base[:, :-1]),
                                   np.asarray(out2[:, :-1]), atol=1e-5)
        assert not np.allclose(np.asarray(base[:, -1]), np.asarray(out2[:, -1]))


class TestPipeline:
    @pytest.fixture(scope="class")
    def pipe(self):
        # special ids must fit the tiny vocab (generate() enforces this)
        return SD15Pipeline(SD15Config.tiny(),
                            tokenizer=ByteTokenizer(max_length=16,
                                                    bos_id=257, eos_id=258))

    @pytest.fixture(scope="class")
    def params(self, pipe):
        return pipe.init_params(seed=0)

    def test_generate_shape_dtype(self, pipe, params):
        imgs = pipe.generate(params, ["a cat"], [""], [1337],
                             width=64, height=64, num_inference_steps=3,
                             scheduler="DDIM")
        assert imgs.shape == (1, 64, 64, 3)
        assert imgs.dtype == np.uint8

    def test_bit_determinism_same_seed(self, pipe, params):
        a = pipe.generate(params, ["a cat"], [""], [1337], width=64, height=64,
                          num_inference_steps=3, scheduler="DDIM")
        b = pipe.generate(params, ["a cat"], [""], [1337], width=64, height=64,
                          num_inference_steps=3, scheduler="DDIM")
        assert (a == b).all()

    def test_seed_changes_output(self, pipe, params):
        a = pipe.generate(params, ["a cat"], [""], [1], width=64, height=64,
                          num_inference_steps=3, scheduler="DDIM")
        b = pipe.generate(params, ["a cat"], [""], [2], width=64, height=64,
                          num_inference_steps=3, scheduler="DDIM")
        assert not (a == b).all()

    def test_53bit_seed_space(self, pipe, params):
        # seeds differing only in bits >32 must differ (taskid2seed is 53-bit)
        s = 0x1FFFFFFFFFFFF0 - 1
        a = pipe.generate(params, ["x"], [""], [s], width=64, height=64,
                          num_inference_steps=2, scheduler="DDIM")
        b = pipe.generate(params, ["x"], [""], [s & 0xFFFFFFFF], width=64,
                          height=64, num_inference_steps=2, scheduler="DDIM")
        assert not (a == b).all()

    def test_batch_matches_singles(self, pipe, params):
        # batching must not change per-sample bytes (batch-invariant numerics
        # hold at fixed shapes because each sample's RNG is independent)
        batch = pipe.generate(params, ["a", "b"], ["", ""], [10, 11],
                              width=64, height=64, num_inference_steps=2,
                              scheduler="DDIM", guidance_scale=[5.0, 9.0])
        single0 = pipe.generate(params, ["a"], [""], [10], width=64, height=64,
                                num_inference_steps=2, scheduler="DDIM",
                                guidance_scale=[5.0])
        np.testing.assert_array_equal(batch[0], single0[0])

    def test_ancestral_scheduler_runs(self, pipe, params):
        imgs = pipe.generate(params, ["a"], [""], [3], width=64, height=64,
                             num_inference_steps=3, scheduler="K_EULER_ANCESTRAL")
        assert imgs.shape == (1, 64, 64, 3)

    def test_input_validation(self, pipe, params):
        with pytest.raises(ValueError, match="align"):
            pipe.generate(params, ["a", "b"], [""], [1], width=64, height=64)
        with pytest.raises(ValueError, match="multiples"):
            pipe.generate(params, ["a"], [""], [1], width=40, height=64)

    def test_tokenizer_vocab_mismatch_is_loud(self, params):
        bad = SD15Pipeline(SD15Config.tiny(), tokenizer=ByteTokenizer(max_length=16))
        with pytest.raises(ValueError, match="vocab_size"):
            bad.generate(params, ["a"], [""], [1], width=64, height=64,
                         num_inference_steps=2, scheduler="DDIM")
