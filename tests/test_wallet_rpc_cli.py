"""Wallet (secp256k1/ECDSA), control RPC, and CLI tests."""
from __future__ import annotations

import json
import urllib.request

import pytest

from arbius_tpu.chain.wallet import Wallet, recover_address
from arbius_tpu.l0.keccak import keccak256


# -- wallet ----------------------------------------------------------------

def test_known_key_address():
    """Golden vector: the universally known hardhat/test key #0."""
    w = Wallet.from_hex(
        "0xac0974bec39a17e36ba4a6b4d238ff944bacb478cbed5efcae784d7bf4f2ff80")
    assert w.address == "0xf39fd6e51aad88f6f4ce6ab8827279cfffb92266"


def test_generate_and_roundtrip():
    w = Wallet.generate()
    assert len(w.private_key) == 32
    assert w.address.startswith("0x") and len(w.address) == 42
    assert Wallet.from_hex("0x" + w.private_key.hex()).address == w.address


def test_sign_recover():
    w = Wallet.from_hex("0x" + "11" * 32)
    h = keccak256(b"arbius solve commitment")
    r, s, rec = w.sign(h)
    assert recover_address(h, r, s, rec) == w.address
    # deterministic (RFC 6979): same hash, same signature
    assert w.sign(h) == (r, s, rec)
    # low-s normalization (EIP-2)
    from arbius_tpu.chain.wallet import N
    assert s <= N // 2


def test_sign_message_eip191():
    w = Wallet.from_hex("0x" + "22" * 32)
    r, s, rec = w.sign_message(b"hello")
    prefixed = b"\x19Ethereum Signed Message:\n5hello"
    assert recover_address(keccak256(prefixed), r, s, rec) == w.address


def test_bad_keys_rejected():
    with pytest.raises(ValueError):
        Wallet.from_hex("0x00")
    with pytest.raises(ValueError):
        Wallet.from_hex("0x" + "00" * 32)  # zero key


# -- control rpc -----------------------------------------------------------

@pytest.fixture
def rpc_node():
    from arbius_tpu.node import MinerNode, MiningConfig, ModelRegistry
    from arbius_tpu.node.rpc import ControlRPC
    from arbius_tpu.chain import Engine, TokenLedger, WAD
    from arbius_tpu.node.chain_client import LocalChain

    tok = TokenLedger()
    eng = Engine(tok, start_time=0)
    tok.mint(Engine.ADDRESS, 600_000 * WAD)
    miner = "0x" + "aa" * 20
    tok.mint(miner, 100 * WAD)
    tok.approve(miner, Engine.ADDRESS, 10**30)
    node = MinerNode(LocalChain(eng, miner), MiningConfig(), ModelRegistry())
    node.boot()
    rpc = ControlRPC(node, port=0)
    rpc.start()
    yield node, rpc
    rpc.stop()


def _get(port, path):
    with urllib.request.urlopen(f"http://127.0.0.1:{port}{path}") as r:
        return json.loads(r.read())


def _post(port, path, payload):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}",
        data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req) as r:
        return json.loads(r.read())


def test_rpc_job_lifecycle(rpc_node):
    node, rpc = rpc_node
    created = _post(rpc.port, "/api/jobs/queue",
                    {"method": "automine", "data": {}, "priority": 7})
    jobs = _get(rpc.port, "/api/jobs/get")
    assert any(j["id"] == created["id"] and j["method"] == "automine"
               for j in jobs)
    _post(rpc.port, "/api/jobs/delete", {"id": created["id"]})
    jobs = _get(rpc.port, "/api/jobs/get")
    assert not any(j["id"] == created["id"] for j in jobs)


def test_rpc_metrics(rpc_node):
    node, rpc = rpc_node
    m = _get(rpc.port, "/api/metrics")
    assert m["solutions_submitted"] == 0
    assert "queue_depth" in m and "solve_latency_p50" in m


def test_rpc_explorer_and_tasks(rpc_node):
    node, rpc = rpc_node
    node.db.store_task("0x" + "ab" * 32, "0x" + "cd" * 32, 5, "0x" + "01" * 20,
                       100, 0, "")
    node.db.store_solution("0x" + "ab" * 32, "0x" + "aa" * 20, 200, False,
                           "0x1220" + "ee" * 32)
    tasks = _get(rpc.port, "/api/tasks")
    assert tasks[0]["taskid"] == "0x" + "ab" * 32
    assert tasks[0]["solution_cid"] == "0x1220" + "ee" * 32
    with urllib.request.urlopen(f"http://127.0.0.1:{rpc.port}/") as r:
        html = r.read().decode()
    assert "arbius-tpu node" in html and "Recent tasks" in html


def test_bridge_token_gateway():
    from arbius_tpu.chain import TokenLedger

    tok = TokenLedger()
    gw = "0x" + "99" * 20
    tok.gateway = gw
    user = "0x" + "01" * 20
    tok.bridge_mint(gw, user, 100)
    assert tok.balance_of(user) == 100 and tok.total_supply == 100
    with pytest.raises(ValueError, match="NOT_GATEWAY"):
        tok.bridge_mint(user, user, 1)
    tok.bridge_burn(gw, user, 40)
    assert tok.balance_of(user) == 60 and tok.total_supply == 60
    with pytest.raises(ValueError, match="NOT_GATEWAY"):
        tok.bridge_burn(user, user, 1)
    from arbius_tpu.chain.token import MAX_SUPPLY
    with pytest.raises(ValueError, match="max supply"):
        tok.bridge_mint(gw, user, MAX_SUPPLY)


def test_rpc_bad_requests(rpc_node):
    _, rpc = rpc_node
    with pytest.raises(urllib.error.HTTPError) as e:
        _post(rpc.port, "/api/jobs/queue", {"data": {}})
    assert e.value.code == 400
    with pytest.raises(urllib.error.HTTPError) as e:
        _get(rpc.port, "/api/nope")
    assert e.value.code == 404


# -- cli -------------------------------------------------------------------

def test_cli_wallet_gen(capsys):
    from arbius_tpu.cli import main

    assert main(["wallet-gen"]) == 0
    out = json.loads(capsys.readouterr().out)
    assert out["address"].startswith("0x")


def test_cli_templates_and_inspect(capsys):
    from arbius_tpu.cli import main

    assert main(["templates"]) == 0
    assert "anythingv3" in capsys.readouterr().out
    assert main(["template", "kandinsky2"]) == 0
    t = json.loads(capsys.readouterr().out)
    assert any(i["variable"] == "prompt" for i in t["inputs"])


def test_cli_emission(capsys):
    from arbius_tpu.cli import main

    assert main(["emission", "--t", "31536000", "--supply", "100000"]) == 0
    out = json.loads(capsys.readouterr().out)
    assert out["targetTs"] == 300000.0
    assert out["diffMul"] == 100.0


def test_cli_validate_config(tmp_path, capsys):
    from arbius_tpu.cli import main

    good = tmp_path / "good.json"
    good.write_text('{"db_path": ":memory:"}')
    assert main(["validate-config", str(good)]) == 0
    bad = tmp_path / "bad.json"
    bad.write_text('{"nope": 1}')
    assert main(["validate-config", str(bad)]) == 1


def test_cli_cid(tmp_path, capsys):
    from arbius_tpu.cli import main

    f = tmp_path / "x.bin"
    f.write_bytes(b"hello world")
    assert main(["cid", str(f)]) == 0
    out = json.loads(capsys.readouterr().out)
    assert out["cid"].startswith("Qm")


def test_jacobian_point_mul_matches_affine_reference():
    """_point_mul runs in Jacobian coordinates (one inversion per
    multiply); the affine _point_add ladder is the reference it must
    never drift from."""
    import hashlib

    from arbius_tpu.chain.wallet import (
        GX,
        GY,
        N,
        _point_add,
        _point_mul,
    )

    def affine_mul(k, point=(GX, GY)):
        result, addend = None, point
        while k:
            if k & 1:
                result = _point_add(result, addend)
            addend = _point_add(addend, addend)
            k >>= 1
        return result

    scalars = [1, 2, 3, N - 1, N // 2, 0x10000000000000000] + [
        int.from_bytes(hashlib.sha256(f"k{i}".encode()).digest(), "big") % N
        for i in range(8)]
    q = _point_mul(987654321)
    for k in scalars:
        assert _point_mul(k) == affine_mul(k)
        assert _point_mul(k, q) == affine_mul(k, q)
    assert _point_mul(0) is None
    assert _point_mul(N) is None      # N·G = infinity
