"""Dapp-slice tests: submit-task form endpoint, task page rendering
outputs by template `output.type`, and address history — the explorer
growing into the reference website's generate / task/[taskid] /
history/[address] pages (`website/src/pages/*`), served by the node.
"""
from __future__ import annotations

import json
import urllib.request

import pytest

from arbius_tpu.node.rpc import ControlRPC

from test_node import build_world, drain, fake_runner, task_input


@pytest.fixture
def dapp(tmp_path):
    eng, tok, chain, node, mid = build_world(store_dir=str(tmp_path / "store"))
    rpc = ControlRPC(node, port=0)
    rpc.start()
    yield eng, chain, node, rpc, mid
    rpc.stop()


def _get_text(port, path):
    with urllib.request.urlopen(f"http://127.0.0.1:{port}{path}") as r:
        return r.read().decode()


def _post(port, path, payload):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}",
        data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req) as r:
        return json.loads(r.read())


def test_submit_form_endpoint_mines_end_to_end(dapp):
    eng, chain, node, rpc, mid = dapp
    res = _post(rpc.port, "/api/tasks/submit",
                {"model": mid, "input": task_input("via the form"), "fee": 0})
    assert res["submitted"] and res["taskid"]
    drain(node)
    assert bytes.fromhex(res["taskid"][2:]) in eng.solutions


def test_submit_rejects_bad_input_before_paying(dapp):
    eng, chain, node, rpc, mid = dapp
    bad = {"model": mid, "input": {"prompt": 42}}  # wrong type
    req = urllib.request.Request(
        f"http://127.0.0.1:{rpc.port}/api/tasks/submit",
        data=json.dumps(bad).encode(),
        headers={"Content-Type": "application/json"})
    with pytest.raises(urllib.error.HTTPError) as e:
        urllib.request.urlopen(req)
    assert e.value.code == 400
    assert len(eng.tasks) == 0


def test_task_page_renders_image_output(dapp):
    eng, chain, node, rpc, mid = dapp
    res = _post(rpc.port, "/api/tasks/submit",
                {"model": mid, "input": task_input("render me")})
    tid = res["taskid"]
    drain(node)
    html = _get_text(rpc.port, f"/task/{tid}")
    assert "solved" in html or "claimed" in html
    assert "render me" in html              # hydrated input shown
    assert "<img src='/ipfs/" in html       # image output per template type
    assert "out-1.png" in html
    # the rendered src actually serves the solution bytes
    src = html.split("<img src='")[1].split("'")[0]
    with urllib.request.urlopen(f"http://127.0.0.1:{rpc.port}{src}") as r:
        data = r.read()
        ctype = r.headers["Content-Type"]
    assert ctype == "image/png"
    sol = eng.solutions[bytes.fromhex(tid[2:])]
    inp = node.db.get_task_input(tid)
    from arbius_tpu.l0.commitment import taskid2seed

    hydrated = dict(inp)
    hydrated["seed"] = taskid2seed(tid)
    assert data == fake_runner(hydrated, hydrated["seed"])["out-1.png"]
    assert sol.validator == chain.address


def test_task_page_unknown_task(dapp):
    _, _, _, rpc, _ = dapp
    html = _get_text(rpc.port, "/task/0x" + "99" * 32)
    assert "task not found" in html


def test_history_page_lists_submitted_and_solved(dapp):
    eng, chain, node, rpc, mid = dapp
    res = _post(rpc.port, "/api/tasks/submit",
                {"model": mid, "input": task_input("history entry")})
    drain(node)
    html = _get_text(rpc.port, f"/history/{chain.address}")
    assert res["taskid"][:18] in html
    assert "1 task(s)" in html
    # unknown address: empty history, not an error
    html = _get_text(rpc.port, "/history/0x" + "77" * 20)
    assert "0 task(s)" in html


def test_explorer_has_submit_form_and_task_links(dapp):
    eng, chain, node, rpc, mid = dapp
    res = _post(rpc.port, "/api/tasks/submit",
                {"model": mid, "input": task_input()})
    drain(node)
    html = _get_text(rpc.port, "/")
    assert "/api/tasks/submit" in html      # the form posts here
    assert f"<option value='{mid}'>" in html
    assert f"/task/{res['taskid']}" in html  # rows link to task pages
    assert f"/history/{chain.address}" in html


def test_models_page_and_api(dapp):
    """Reference dapp's models page parity: /api/models inventory +
    rendered /models view, linked from the explorer."""
    eng, chain, node, rpc, mid = dapp
    models = json.loads(_get_text(rpc.port, "/api/models"))
    assert len(models) == len(node.registry.ids())
    m = next(x for x in models if x["id"] == mid)
    assert m["outputs"] and "template_title" in m and "min_fee" in m
    html = _get_text(rpc.port, "/models")
    assert "Registered models" in html and mid[:22] in html
    assert "/models" in _get_text(rpc.port, "/")


def test_raw_tx_passthrough_spends_user_wallet():
    """generate.tsx user-wallet parity: a SECOND wallet signs submitTask
    offline, the dapp POSTs the raw bytes to /api/tx/raw, the node
    forwards them verbatim — and the devnet-recovered task owner is the
    USER's address, not the node's. LocalChain nodes reject with a clear
    error (no raw-tx surface to forward to)."""
    import urllib.error

    from arbius_tpu.chain import WAD, Engine, TokenLedger
    from arbius_tpu.chain.devnet import DevnetNode
    from arbius_tpu.chain.rlp import Eip1559Tx
    from arbius_tpu.chain.rpc_client import ENGINE_FNS, EngineRpcClient, call_data
    from arbius_tpu.chain.wallet import Wallet
    from arbius_tpu.node.config import AutomineConfig, MiningConfig, ModelConfig
    from arbius_tpu.node.node import MinerNode
    from arbius_tpu.node.rpc_chain import RpcChain
    from arbius_tpu.node.solver import ModelRegistry, RegisteredModel
    from arbius_tpu.templates.engine import load_template

    from test_rpc_chain import CHAIN_ID, DevnetTransport, KEY_MINER, KEY_USER

    tok = TokenLedger()
    eng = Engine(tok, start_time=1000)
    tok.mint(Engine.ADDRESS, 600_000 * WAD)
    dev = DevnetNode(eng, chain_id=CHAIN_ID)
    miner, user = Wallet.from_hex(KEY_MINER), Wallet.from_hex(KEY_USER)
    tok.mint(miner.address, 1000 * WAD)
    tok.mint(user.address, 1000 * WAD)
    mid_bytes = eng.register_model(user.address, user.address, 0,
                                   b'{"meta":{"title":"t"}}')
    mid = "0x" + mid_bytes.hex()

    miner_client = EngineRpcClient(DevnetTransport(dev), dev.engine_address,
                                   miner, chain_id=CHAIN_ID)
    chain = RpcChain(miner_client, dev.token_address)
    registry = ModelRegistry()
    registry.register(RegisteredModel(id=mid,
                                      template=load_template("anythingv3"),
                                      runner=fake_runner))
    cfg = MiningConfig(models=(ModelConfig(id=mid, template="anythingv3"),),
                       automine=AutomineConfig())
    node = MinerNode(chain, cfg, registry)
    rpc = ControlRPC(node, port=0)
    rpc.start()
    try:
        # the user signs submitTask with THEIR key; the node never sees it
        signature, types = ENGINE_FNS["submitTask"]
        tx = Eip1559Tx(
            chain_id=CHAIN_ID, nonce=0, max_priority_fee_per_gas=1,
            max_fee_per_gas=100, gas_limit=2_000_000,
            to=dev.engine_address, value=0,
            data=call_data(signature, types, [
                0, user.address, mid, 0, b'{"prompt":"mine","negative_prompt":""}']))
        raw = "0x" + tx.sign(user).hex()
        res = _post(rpc.port, "/api/tx/raw", {"raw": raw})
        assert res["submitted"] and res["txhash"].startswith("0x")
        task = next(iter(eng.tasks.values()))
        assert task.owner == user.address.lower()

        # malformed input: clean 400, nothing forwarded
        import pytest as _pytest
        with _pytest.raises(urllib.error.HTTPError) as e:
            _post(rpc.port, "/api/tx/raw", {"raw": "not hex"})
        assert e.value.code == 400
    finally:
        rpc.stop()


def test_raw_tx_rejected_on_localchain(dapp):
    import urllib.error

    eng, chain, node, rpc, mid = dapp
    req = urllib.request.Request(
        f"http://127.0.0.1:{rpc.port}/api/tx/raw",
        data=json.dumps({"raw": "0x02dead"}).encode(),
        headers={"Content-Type": "application/json"})
    with pytest.raises(urllib.error.HTTPError) as e:
        urllib.request.urlopen(req)
    assert e.value.code == 400
    assert len(eng.tasks) == 0


def test_chain_info_and_eip1193_page_path(dapp):
    """The browser-wallet path: /api/chain/info hands the page what it
    needs, the served JS ABI-encodes submitTask identically to the
    protocol encoder, and the page actually embeds the EIP-1193 flow."""
    eng, chain, node, rpc, mid = dapp
    info = json.loads(_get_text(rpc.port, "/api/chain/info"))
    from arbius_tpu.chain.rpc_client import ENGINE_FNS, selector
    sig, types = ENGINE_FNS["submitTask"]
    assert info["submit_task_selector"] == "0x" + selector(sig).hex()
    assert info["engine"]  # LocalChain exposes Engine.ADDRESS

    # mirror the page JS's encoding in python; it must equal the
    # protocol ABI encoder's calldata byte-for-byte
    from arbius_tpu.chain.rpc_client import call_data
    owner = "0x" + "42" * 20
    fee = 123
    input_bytes = json.dumps(task_input("via metamask")).encode()
    expected = call_data(sig, types, [0, owner, mid, fee, input_bytes])
    ih = input_bytes.hex()
    js_built = (
        info["submit_task_selector"]
        + format(0, "064x")
        + owner[2:].lower().rjust(64, "0")
        + mid[2:].rjust(64, "0")
        + format(fee, "064x")
        + format(0xA0, "064x")
        + format(len(input_bytes), "064x")
        + ih.ljust((len(ih) + 63) // 64 * 64, "0"))
    assert js_built == "0x" + expected.hex()

    page = _get_text(rpc.port, "/")
    assert "window.ethereum" in page and "eth_requestAccounts" in page
    assert "eth_sendTransaction" in page
