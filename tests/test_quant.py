"""quantserve — int8/fp8 execution modes under the determinism gate.

The contracts under test (docs/quantization.md):

  * weight quantization is symmetric per-output-channel with f32
    scales; dequant passes through f32 (GRAPH407's beat) and the bf16
    mode is the pre-quant tree byte-for-byte (untouched).
  * the EQuARX-style quantized ring allreduce keeps every replica
    bit-identical, is deterministic run-to-run, and degrades to the
    plain psum at bf16.
  * `estimate_collective_bytes` reports actual wire bytes when the tp
    allreduce runs quantized (`wire_dtype` — the obs satellite).
  * a precision mode is a determinism class: own bucket keys, own cost
    rows (sqlite migration included), own AOT cache keys, own CIDs —
    and dp-sharding stays byte-identical WITHIN a mode.
  * simnet clean + crash-restart hold every SIM invariant at int8.
"""
import json
import pathlib
import sqlite3

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from arbius_tpu import quant
from arbius_tpu.node.config import (
    ConfigError,
    MiningConfig,
    ModelConfig,
    PrecisionConfig,
    load_config,
)

REPO = pathlib.Path(__file__).resolve().parent.parent


# -- quant core -------------------------------------------------------------

@pytest.mark.parametrize("mode", ["int8", "fp8"])
def test_quantize_round_trip_and_scale_contract(mode):
    w = np.random.RandomState(0).randn(16, 8).astype(np.float32)
    q = quant.quantize_leaf(w, mode)
    assert quant.is_quantized_leaf(q)
    assert q["qs"].dtype == jnp.float32          # scales are f32, always
    assert q["qs"].shape == (8,)                 # per-OUTPUT-channel
    assert q["qv"].dtype == quant.storage_dtype(mode)
    back = np.asarray(quant.dequantize_leaf(q))
    assert back.dtype == np.float32
    # error envelope: int8's grid step is absmax/127 per channel; fp8
    # e4m3 rounds RELATIVE (3 mantissa bits → one part in 16)
    bound = quant.INT8_BOUND if mode == "int8" else quant.FP8_BOUND
    step = np.abs(w).max(axis=0) / bound
    assert np.all(np.abs(back - w) <=
                  np.maximum(1.001 * step, np.abs(w) / 16.0))


def test_quantize_tree_eligibility_and_bf16_identity():
    tree = {"layer": {"kernel": jnp.ones((4, 4)), "bias": jnp.ones((4,)),
                      "scale": jnp.ones((4,))},
            "ids": jnp.arange(4)}                # integer leaf: untouched
    assert quant.quantize_tree(tree, "bf16") is tree  # byte-identical path
    qt = quant.quantize_tree(tree, "int8")
    assert quant.is_quantized_leaf(qt["layer"]["kernel"])
    # 0/1-D leaves and integer leaves stay full-width
    assert qt["layer"]["bias"].dtype == jnp.float32
    assert qt["ids"].dtype == tree["ids"].dtype
    back = quant.dequantize_tree(qt)
    assert jax.tree_util.tree_structure(back) == \
        jax.tree_util.tree_structure(tree)
    # dequantize_tree is a no-op on an unquantized tree
    assert quant.dequantize_tree(tree)["layer"]["bias"] is \
        tree["layer"]["bias"]


def test_abstract_quantized_matches_concrete_structure():
    tree = {"k": jnp.ones((8, 4))}
    concrete = quant.quantize_tree(tree, "int8")
    abstract = quant.abstract_quantized(jax.eval_shape(lambda: tree),
                                        "int8")
    assert jax.tree_util.tree_structure(abstract) == \
        jax.tree_util.tree_structure(concrete)
    assert abstract["k"]["qv"].dtype == concrete["k"]["qv"].dtype
    assert abstract["k"]["qs"].shape == concrete["k"]["qs"].shape


def test_validate_mode_one_sentence_error():
    with pytest.raises(ValueError) as e:
        quant.validate_mode("int4", where="precision.default")
    assert "precision.default" in str(e.value)
    assert "int8" in str(e.value)
    assert quant.mode_tag("bf16") == ""          # pre-quant tags unchanged
    assert quant.mode_tag("int8") == ".int8"


def test_quantized_dot_accumulates_wide():
    qx = jnp.full((2, 4), 100, jnp.int8)
    qw = jnp.full((4, 2), 100, jnp.int8)
    out = quant.quantized_dot(qx, qw, jnp.ones((2,)), jnp.ones((2,)),
                              "int8")
    # 4 * 100 * 100 = 40000 wraps in int8 — int32 accumulation doesn't
    assert out.dtype == jnp.float32
    assert float(out[0, 0]) == 40000.0
    with pytest.raises(ValueError):
        quant.quantized_dot(qx, qw, jnp.ones((2,)), jnp.ones((2,)),
                            "bf16")


# -- quantized ring allreduce ----------------------------------------------

def _ring_allreduce(x, tp, mode):
    from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P

    from arbius_tpu.parallel.collectives import quantized_ring_allreduce
    from arbius_tpu.parallel.mesh import MeshSpec, build_mesh

    mesh = build_mesh(MeshSpec(tp=tp), devices=jax.devices()[:tp])
    fn = jax.jit(shard_map(
        lambda xs: quantized_ring_allreduce(xs, "tp", mode=mode),
        mesh=mesh, in_specs=P("tp"), out_specs=P("tp"), check_rep=False))
    return np.asarray(fn(x))


@pytest.mark.parametrize("tp", [2, 4])
def test_quantized_ring_allreduce_replicas_identical_and_accurate(tp):
    x = np.random.RandomState(1).randn(tp, 6, 5).astype(np.float32)
    ref = x.sum(axis=0)
    out = _ring_allreduce(x, tp, "int8")
    # every replica bit-identical — a diverged replica forks CIDs
    for i in range(1, tp):
        assert np.array_equal(out[i], out[0])
    # deterministic run-to-run (fixed ring schedule)
    assert np.array_equal(out, _ring_allreduce(x, tp, "int8"))
    # int8 wire error well under bf16's own mantissa step at this range
    assert np.max(np.abs(out[0] - ref)) < 0.05 * np.max(np.abs(ref))


def test_quantized_ring_allreduce_bf16_degrades_to_psum():
    x = np.random.RandomState(2).randn(2, 4, 3).astype(np.float32)
    out = _ring_allreduce(x, 2, "bf16")
    assert np.allclose(out[0], x.sum(axis=0), atol=1e-5)


# -- wire-byte accounting (obs satellite) -----------------------------------

def test_estimate_collective_bytes_wire_dtype_override():
    from arbius_tpu.parallel.mesh import MeshSpec, build_mesh
    from arbius_tpu.parallel.meshsolve import estimate_collective_bytes
    from arbius_tpu.parallel.sharding import shard_params

    mesh = build_mesh(MeshSpec(dp=2, tp=2), devices=jax.devices()[:4])
    params = {"w": jnp.ones((8, 8), jnp.float32)}
    placed = shard_params(params, mesh,
                          ((r".*w$", __import__("jax").sharding
                            .PartitionSpec(None, "tp")),))
    full = estimate_collective_bytes(mesh, (2, 8, 8), np.float32,
                                     params=placed)
    wired = estimate_collective_bytes(mesh, (2, 8, 8), np.float32,
                                      params=placed,
                                      wire_dtype=np.int8)
    # tp term: 2·(tp-1)/tp · elements · width — 4-byte vs 1-byte wire
    assert full["tp"] == 2 * 8 * 8 * 4 * 1 // 2
    assert wired["tp"] == 2 * 8 * 8 * 1 * 1 // 2
    assert wired["tp"] * 4 == full["tp"]
    # the dp output-gather term is untouched by the tp wire override
    assert full["dp"] == wired["dp"]


def test_quantized_probe_reports_quantized_tp_wire_bytes():
    """The int8 img probe's tp slab is 1-byte on the wire — the metered
    estimate must come out strictly below the bf16 probe's."""
    from arbius_tpu.parallel.mesh import MeshSpec, build_mesh
    from arbius_tpu.parallel.meshsolve import ShardedImageProbe
    from arbius_tpu.obs import Obs, use_obs

    def tp_bytes(mode):
        mesh = build_mesh(MeshSpec(dp=2, tp=2), devices=jax.devices()[:4])
        obs = Obs(journal_capacity=16)
        with use_obs(obs):
            probe = ShardedImageProbe(mesh=mesh, mode=mode)
            probe.run_batch([({"prompt": f"t{i}"}, i) for i in range(2)])
        c = obs.registry.counter("arbius_collective_bytes_total",
                                 labelnames=("axis",))
        return c.value(axis="tp")

    assert 0 < tp_bytes("int8") < tp_bytes("bf16")


# -- precision config -------------------------------------------------------

def test_precision_config_validation_is_one_sentence():
    with pytest.raises(ConfigError) as e:
        load_config('{"precision": {"default": "fp4"}}')
    assert "fp4" in str(e.value)
    with pytest.raises(ConfigError):
        load_config('{"precision": {"templates": {"anythingv3": "x"}}}')
    with pytest.raises(ConfigError):
        load_config('{"precision": {"templates": ["int8"]}}')
    cfg = load_config('{"precision": {"default": "int8", '
                      '"templates": {"kandinsky2": "bf16"}}}')
    assert cfg.precision.mode_for("anythingv3") == "int8"
    assert cfg.precision.mode_for("kandinsky2") == "bf16"
    # the default default is the pre-quant node
    assert MiningConfig().precision.mode_for("anythingv3") == "bf16"


def test_example_config_ships_precision_block():
    raw = (REPO / "MiningConfig.example.json").read_text()
    cfg = load_config(raw)
    assert cfg.precision.default == "bf16"
    assert json.loads(raw)["precision"]["default"] == "bf16"


def test_rvm_rejects_quantized_modes_at_boot():
    from arbius_tpu.node.factory import build_registry

    cfg = MiningConfig(
        models=(ModelConfig(id="0x" + "22" * 32,
                            template="robust_video_matting", tiny=True,
                            golden={"input": {}, "seed": 0, "cid": "0x0",
                                    "probe_video": "2x16x16"}),),
        precision=PrecisionConfig(default="int8"),
        compile_cache_dir=None)
    with pytest.raises(ConfigError) as e:
        build_registry(cfg)
    assert "robust_video_matting" in str(e.value)


# -- mode is a bucket/cost identity -----------------------------------------

def test_bucket_key_carries_mode():
    from arbius_tpu.node.solver import bucket_key, bucket_mode

    h = {"width": 64, "height": 64, "num_inference_steps": 2,
         "scheduler": "DDIM"}
    k_bf = bucket_key("0xmm", h)
    k_q = bucket_key("0xmm", h, "int8")
    assert k_bf != k_q
    assert bucket_mode(k_bf) == "bf16"
    assert bucket_mode(k_q) == "int8"
    # pre-quant 6-tuples (persisted keys, old tests) read as bf16
    assert bucket_mode(k_bf[:6]) == "bf16"
    from arbius_tpu.node.costmodel import bucket_str

    assert bucket_str(k_bf) == bucket_str(k_q)  # shape part, mode aside


def test_cost_model_db_migration_preserves_rows_and_separates_modes(
        tmp_path):
    """A pre-quant `cost_model` table migrates in place: old rows stamp
    mode='bf16', and rows at a second mode can then coexist (the old
    3-column primary key could not hold both)."""
    from arbius_tpu.node.db import NodeDB

    path = str(tmp_path / "old.sqlite")
    conn = sqlite3.connect(path)
    conn.executescript("""
        CREATE TABLE cost_model (
            model TEXT, bucket TEXT, layout TEXT,
            chip_seconds REAL, samples INT, updated INT,
            PRIMARY KEY (model, bucket, layout));
        INSERT INTO cost_model VALUES
            ('0xaa', '64x64.s2.DDIM.f-', 'single', 3.5, 9, 77);
    """)
    conn.commit()
    conn.close()
    db = NodeDB(path)
    rows = db.load_cost_rows()
    assert rows == [("0xaa", "64x64.s2.DDIM.f-", "single", "bf16",
                     3.5, 9, 77)]
    db.upsert_cost_rows([("0xaa", "64x64.s2.DDIM.f-", "single", "int8",
                          1.5, 4, 88)])
    both = db.load_cost_rows()
    assert len(both) == 2 and {r[3] for r in both} == {"bf16", "int8"}
    db.close()
    # idempotent: reopening an already-migrated file is a no-op
    db2 = NodeDB(path)
    assert len(db2.load_cost_rows()) == 2
    db2.close()


# -- per-mode program identity (AOT keys, CIDs) -----------------------------

def test_bf16_and_int8_programs_hash_to_different_aot_keys():
    """The coldboot satellite: cross-mode executable poisoning is
    structurally impossible — the graphlint fingerprint differs, so the
    derived cache key differs even with identical env and args."""
    from arbius_tpu.aotcache import env_signature
    from arbius_tpu.aotcache.store import derive_key
    from arbius_tpu.analysis.graph.fingerprint import fingerprint
    from arbius_tpu.parallel.meshsolve import (
        _PROBE_DIM,
        ShardedImageProbe,
    )

    env = env_signature()
    fps = {}
    for mode in ("bf16", "int8"):
        probe = ShardedImageProbe(mode=mode)
        p = jax.ShapeDtypeStruct((_PROBE_DIM, _PROBE_DIM), jnp.float32)
        if mode != "bf16":
            p = quant.abstract_quantized(p, mode)
        fps[mode] = fingerprint(jax.make_jaxpr(probe._fn(1))(
            p, jax.ShapeDtypeStruct((1,), jnp.uint32)))
    assert fps["bf16"] != fps["int8"]
    assert derive_key(fps["bf16"], env, "sig") != \
        derive_key(fps["int8"], env, "sig")


def test_probe_int8_layout_invariance_and_mode_separation():
    """Within int8: mesh-off == dp2 byte-identical (dp shards samples;
    the quantized weights are replicated identical bits). Across modes:
    different bytes — a mode is its own determinism class."""
    from arbius_tpu.parallel.mesh import MeshSpec, build_mesh
    from arbius_tpu.parallel.meshsolve import ShardedImageProbe

    items = [({"prompt": f"t{i}"}, 1000 + i) for i in range(4)]

    def run(mesh_cfg, mode):
        mesh = None
        if mesh_cfg:
            n = int(np.prod(list(mesh_cfg.values())))
            mesh = build_mesh(MeshSpec(**mesh_cfg),
                              devices=jax.devices()[:n])
        probe = ShardedImageProbe(mesh=mesh, mode=mode)
        return [f["out-1.png"] for f in probe.run_batch(items)]

    off = run(None, "int8")
    assert off == run({"dp": 2}, "int8")
    assert off == run({"dp": 2, "tp": 2}, "int8")  # concat-only tp
    assert off != run(None, "bf16")
    assert off != run(None, "fp8")


def test_seq_probe_quantized_allreduce_is_deterministic():
    """The dp2.sp2 int8 seq probe carries a REAL quantized ring
    allreduce (its golden pins the program) — run-to-run byte
    equality is the determinism claim for the quantized collective."""
    from arbius_tpu.parallel.mesh import MeshSpec, build_mesh
    from arbius_tpu.parallel.meshsolve import ShardedSeqProbe

    items = [({"prompt": "a"}, 1), ({"prompt": "b"}, 2)]

    def run():
        mesh = build_mesh(MeshSpec(dp=2, sp=2), devices=jax.devices()[:4])
        probe = ShardedSeqProbe(mesh=mesh, mode="int8")
        return [f["out-1.png"] for f in probe.run_batch(items)]

    assert run() == run()


# -- simnet at int8 (acceptance) --------------------------------------------

def test_simnet_clean_green_at_int8_and_pipeline_invariant():
    """SIM101-112 hold at int8, and pipeline on/off reach identical
    CIDs within the mode — the schedule still never touches bytes."""
    from arbius_tpu.sim.harness import run_scenario
    from arbius_tpu.sim.invariants import check_all
    from arbius_tpu.sim.scenario import get_scenario

    clean = get_scenario("clean").with_tasks(4)
    on = run_scenario(clean, 3, mesh={}, precision="int8")
    findings = check_all(on)
    assert not findings, "\n".join(f.text() for f in findings)
    off = run_scenario(clean, 3, mesh={}, precision="int8",
                       pipeline=False)
    assert not check_all(off)
    cids = lambda r: {t: s.cid for t, s in r.engine.solutions.items()}
    assert cids(on) == cids(off)
    # and the mode really ran: bf16 CIDs differ
    bf = run_scenario(clean, 3, mesh={}, precision="bf16")
    assert cids(on) != cids(bf)


def test_simnet_crash_restart_green_at_int8(tmp_path):
    from arbius_tpu.sim.harness import run_scenario
    from arbius_tpu.sim.invariants import check_all
    from arbius_tpu.sim.scenario import get_scenario

    res = run_scenario(get_scenario("crash-restart"), 5, mesh={},
                       precision="int8",
                       db_path=str(tmp_path / "sim.sqlite"))
    findings = check_all(res)
    assert not findings, "\n".join(f.text() for f in findings)
    assert res.quiescent
