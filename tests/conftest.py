"""Test harness config.

Multi-chip behavior is tested on a virtual 8-device CPU mesh (the driver
separately dry-run-compiles the multichip path): force the host platform
BEFORE jax is imported anywhere.
"""
import os

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()

import pathlib

import pytest

FIXTURES = pathlib.Path(__file__).parent / "fixtures"


@pytest.fixture(scope="session")
def fixtures_dir() -> pathlib.Path:
    return FIXTURES
