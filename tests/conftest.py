"""Test harness config.

Multi-chip behavior is tested on a virtual 8-device CPU mesh (the driver
separately dry-run-compiles the multichip path). The environment's
sitecustomize registers the remote-TPU `axon` backend in every
interpreter with JAX_PLATFORMS=axon already cached, so env vars alone
are too late — `force_cpu_devices` forces the jax config and neuters
non-CPU backend factories before any backend init.
"""
import pathlib

from arbius_tpu.utils import force_cpu_devices

force_cpu_devices(8)

import pytest

FIXTURES = pathlib.Path(__file__).parent / "fixtures"


@pytest.fixture(scope="session")
def fixtures_dir() -> pathlib.Path:
    return FIXTURES
