"""Test harness config.

Multi-chip behavior is tested on a virtual 8-device CPU mesh (the driver
separately dry-run-compiles the multichip path): force the host platform
BEFORE jax is imported anywhere.
"""
import os

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()

# The environment's sitecustomize imports jax at interpreter startup to
# register the `axon` remote-TPU backend, so jax has ALREADY cached
# JAX_PLATFORMS=axon from the outer environment by the time this conftest
# runs — the os.environ assignment above is too late on its own. Force the
# config directly, and neuter the axon factory so backend discovery can't
# touch the (possibly unhealthy) TPU tunnel from a CPU-only test run.
import jax

jax.config.update("jax_platforms", "cpu")
try:
    import jax._src.xla_bridge as _xb

    _xb._discover_and_register_pjrt_plugins()
    for _name in list(getattr(_xb, "_backend_factories", {})):
        if _name not in ("cpu", "tpu"):
            _xb.register_backend_factory(
                _name, lambda: None, priority=-100, fail_quietly=True)
except Exception:
    pass

import pathlib

import pytest

FIXTURES = pathlib.Path(__file__).parent / "fixtures"


@pytest.fixture(scope="session")
def fixtures_dir() -> pathlib.Path:
    return FIXTURES
