"""Test harness config.

Multi-chip behavior is tested on a virtual 8-device CPU mesh (the driver
separately dry-run-compiles the multichip path). The environment's
sitecustomize registers the remote-TPU `axon` backend in every
interpreter with JAX_PLATFORMS=axon already cached, so env vars alone
are too late — `force_cpu_devices` forces the jax config and neuters
non-CPU backend factories before any backend init.
"""
import pathlib

from arbius_tpu.utils import force_cpu_devices

force_cpu_devices(8)

import time

import pytest

FIXTURES = pathlib.Path(__file__).parent / "fixtures"

# tier-1 wall budget (ROADMAP.md): the suite must finish inside the
# 870 s driver timeout; warn loudly once the 'not slow' selection
# crosses this, so headroom erosion is visible in EVERY run instead of
# surfacing as a CI timeout three PRs later
TIER1_WARN_WALL_S = 700.0


@pytest.fixture(scope="session")
def fixtures_dir() -> pathlib.Path:
    return FIXTURES


def pytest_sessionstart(session):
    session.config._arbius_wall_t0 = time.time()


def pytest_terminal_summary(terminalreporter, exitstatus, config):
    t0 = getattr(config, "_arbius_wall_t0", None)
    if t0 is None:
        return
    wall = time.time() - t0
    markexpr = getattr(config.option, "markexpr", "") or ""
    tier1 = "not slow" in markexpr
    terminalreporter.write_line(
        f"suite wall: {wall:.1f} s"
        + (f" (tier-1 budget: warn {TIER1_WARN_WALL_S:.0f} s, "
           "driver timeout 870 s)" if tier1 else ""))
    if tier1 and wall > TIER1_WARN_WALL_S:
        terminalreporter.write_line(
            f"WARNING: tier-1 suite wall {wall:.1f} s exceeds the "
            f"{TIER1_WARN_WALL_S:.0f} s headroom line — the driver "
            "kills the run at 870 s; move tests to @pytest.mark.slow "
            "or shrink fixtures (ROADMAP.md tier-1 budget)",
            red=True, bold=True)
