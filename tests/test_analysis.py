"""detlint — rule fixtures, escape hatches, golden JSON, and the tier-1
package self-check that enforces the determinism invariant on every PR.

Each rule gets positive + negative snippets; the suppression/baseline/
enforce machinery gets its own section; the self-check runs the analyzer
over the whole `arbius_tpu/` package against the checked-in baseline and
fails on any non-baselined finding — which is the actual guardrail: add
an unseeded RNG call or wall-clock read to the solve path and THIS file
goes red.
"""
import json
import os
import pathlib
import subprocess
import sys

import pytest

from arbius_tpu.analysis import (
    Baseline,
    Finding,
    analyze_paths,
    analyze_source,
)
from arbius_tpu.analysis import baseline as baseline_mod
from arbius_tpu.analysis.cli import main as cli_main

REPO = pathlib.Path(__file__).resolve().parent.parent
FIXDIR = pathlib.Path(__file__).parent / "fixtures" / "detlint"

sys.path.insert(0, str(REPO / "tools"))


def rules_of(findings: list[Finding]) -> list[str]:
    return [f.rule for f in findings]


def check(source: str) -> list[Finding]:
    return analyze_source(source, "snippet.py")


# -- determinism rules ------------------------------------------------------

def test_det101_wall_clock_positive_and_negative():
    hits = check("import time\nt = time.time()\n")
    assert rules_of(hits) == ["DET101"]
    assert hits[0].line == 2
    assert check("import datetime\nd = datetime.datetime.now()\n")
    assert not check("t = chain.now\n")
    assert not check("import time\ntime.sleep(1)\n")  # sleep reads nothing


def test_det101_import_aliases_cannot_evade():
    # `import time as _t` / `from time import time` must be caught —
    # literal-spelling matching would let a one-line alias defeat the
    # enforce[] guarantee (node/node.py already uses `import time as
    # _time` style)
    assert rules_of(check(
        "import time as _t\nx = _t.time()\n")) == ["DET101"]
    assert rules_of(check(
        "from time import time\nx = time()\n")) == ["DET101"]
    assert rules_of(check(
        "from time import time as now\nx = now()\n")) == ["DET101"]
    assert rules_of(check(
        "from datetime import datetime\nd = datetime.now()\n")) == \
        ["DET101"]


def test_rule_aliases_across_families():
    assert rules_of(check(
        "from json import dumps\nb = dumps(obj)\n")) == ["DET104"]
    assert rules_of(check(
        "from os import urandom\nk = urandom(8)\n")) == ["DET102"]
    assert rules_of(check(
        "from glob import glob\nxs = glob('*.png')\n")) == ["DET103"]
    src = ("from jax import jit\n"
           "@jit\n"
           "def f(x):\n    return x.item()\n")
    assert rules_of(check(src)) == ["JIT201"]


def test_det102_rng_positive_and_negative():
    assert rules_of(check("import random\nx = random.random()\n")) == \
        ["DET102"]
    assert check("import os\nk = os.urandom(32)\n")
    assert check("import numpy as np\nr = np.random.default_rng()\n")
    # seeded constructors and keyed jax streams are the sanctioned path
    assert not check("import numpy as np\nr = np.random.default_rng(7)\n")
    assert not check("import jax\nk = jax.random.PRNGKey(seed)\n")


def test_det101_suffix_needs_dot_boundary():
    # a variable merely named *_datetime must not be a wall-clock hit
    assert not check("x = my_datetime.now()\n")
    assert rules_of(check(
        "from datetime import date\nd = date.today()\n")) == ["DET101"]


def test_det102_deterministic_module_members_exempt():
    assert not check("import secrets\nok = secrets.compare_digest(a, b)\n")
    assert not check("import random\ns = random.getstate()\n")
    assert rules_of(check(
        "import secrets\nk = secrets.token_hex(8)\n")) == ["DET102"]


def test_det103_fs_order():
    assert rules_of(check(
        "import os\nfor f in os.listdir(d):\n    h(f)\n")) == ["DET103"]
    assert check("p = root.iterdir()\n")
    assert check("import glob\nxs = glob.glob('*.png')\n")
    assert not check("import os\nfor f in sorted(os.listdir(d)):\n    h(f)\n")
    assert not check(
        "names = sorted(p.name for p in root.iterdir())\n")


def test_det104_unsorted_dumps():
    assert rules_of(check("import json\nb = json.dumps(obj)\n")) == \
        ["DET104"]
    assert not check("import json\nb = json.dumps(obj, sort_keys=True)\n")
    # literal dicts with constant keys serialize in source order
    assert not check("import json\nb = json.dumps({'a': 1, 'b': x})\n")


def test_det104_explicit_false_is_flagged():
    assert rules_of(check(
        "import json\nb = json.dumps(obj, sort_keys=False)\n")) == \
        ["DET104"]
    # a non-constant value is the caller's responsibility
    assert not check("import json\nb = json.dumps(obj, sort_keys=flag)\n")


def test_det105_set_iteration():
    assert rules_of(check(
        "for x in {'a', 'b'}:\n    f(x)\n")) == ["DET105"]
    assert check("ys = [f(x) for x in set(xs)]\n")
    assert not check("for x in sorted({'a', 'b'}):\n    f(x)\n")
    assert not check("for x in xs:\n    f(x)\n")


def test_det106_runtime_env_mutation():
    assert rules_of(check(
        "def f():\n    jax.config.update('jax_enable_x64', True)\n")) == \
        ["DET106"]
    assert check("def f():\n    os.environ['JAX_PLATFORMS'] = 'cpu'\n")
    # module-level configuration is boot-time and fine
    assert not check("jax.config.update('jax_enable_x64', True)\n")


# -- jit purity rules -------------------------------------------------------

def test_jit201_host_escape_decorated():
    src = ("import jax\nimport numpy as np\n"
           "@jax.jit\n"
           "def f(x):\n"
           "    print('hi')\n"
           "    y = np.asarray(x)\n"
           "    z = x.item()\n"
           "    return float(x)\n")
    assert rules_of(check(src)) == ["JIT201"] * 4


def test_jit201_wrapped_and_lambda_forms():
    # the jax.jit(with_cast(_init, dtype)) idiom used by every pipeline
    src = ("import jax\n"
           "def _init(k):\n"
           "    return x.item()\n"
           "jitted = jax.jit(with_cast(_init, dtype))\n")
    assert rules_of(check(src)) == ["JIT201"]
    assert rules_of(check(
        "import jax\ng = jax.jit(lambda x: x.tolist())\n")) == ["JIT201"]


def test_jit201_negative_outside_jit():
    assert not check(
        "import numpy as np\ndef f(x):\n    return np.asarray(x)\n")
    # float() on a literal is not a tracer cast
    assert not check("import jax\n@jax.jit\ndef f(x):\n"
                     "    return x * float(0.5)\n")


def test_jit_collection_ignores_static_args():
    # only the FIRST jit(...) argument is the compiled function; a
    # config factory passed alongside must not be poisoned
    src = ("import jax\n"
           "def cfg():\n    print('building config')\n"
           "def build(c, n):\n    return c\n"
           "step = jax.jit(build(identity, 3), static_argnums=cfg)\n")
    assert not check(src)


def test_jit202_global_mutation():
    src = ("import jax\n@jax.jit\ndef f(x):\n"
           "    global _cache\n    _cache = x\n    return x\n")
    assert rules_of(check(src)) == ["JIT202"]
    assert not check("def f(x):\n    global _cache\n    _cache = x\n")


# -- concurrency rules ------------------------------------------------------

_THREADED = """\
import threading

class Worker:
    def __init__(self):
        self.state = "idle"
        self._t = threading.Thread(target=self._run, daemon=True)

    def set_state(self, s):
        self.state = s

    def _run(self):
        while self.state != "stop":
            pass
"""


def test_conc301_unlocked_shared_attribute():
    hits = check(_THREADED)
    assert rules_of(hits) == ["CONC301"]
    assert "self.state" in hits[0].message


def test_conc301_lock_on_both_sides_is_clean():
    src = _THREADED.replace(
        "        self.state = s",
        "        with self._lock:\n            self.state = s",
    ).replace(
        "        while self.state != \"stop\":\n            pass",
        "        with self._lock:\n            s = self.state",
    ).replace(
        "        self.state = \"idle\"",
        "        self.state = \"idle\"\n"
        "        self._lock = threading.Lock()",
    )
    assert not check(src)


def test_conc301_init_writes_and_primitives_exempt():
    src = ("import threading\n"
           "class W:\n"
           "    def __init__(self):\n"
           "        self.stop = threading.Event()\n"
           "        self.name = 'w'\n"
           "        self._t = threading.Thread(target=self._run)\n"
           "    def _run(self):\n"
           "        while not self.stop.wait(1):\n"
           "            f(self.name)\n")
    assert not check(src)


def test_conc301_init_reads_exempt_too():
    # a read in __init__ happens-before Thread.start(); it cannot race
    src = ("import threading\n"
           "class W:\n"
           "    def __init__(self):\n"
           "        self.state = 'idle'\n"
           "        print(self.state)\n"
           "        self._t = threading.Thread(target=self._run)\n"
           "    def _run(self):\n"
           "        self.state = 'busy'\n")
    assert not check(src)


def test_conc301_only_threaded_classes_analyzed():
    assert not check(
        "class Plain:\n"
        "    def a(self):\n        self.x = 1\n"
        "    def b(self):\n        return self.x\n")


def test_conc301_lock_substring_names_do_not_count_as_held():
    # `clock` contains "lock" but holds no lock — a `with self.clock:`
    # block is not synchronization and must not hide the race (the old
    # substring heuristic was fooled by blocked/clock/lockfile names)
    src = _THREADED.replace(
        "        self.state = s",
        "        with self.clock:\n            self.state = s",
    ).replace(
        "        while self.state != \"stop\":\n            pass",
        "        with self.clock:\n            s = self.state",
    ).replace(
        "        self.state = \"idle\"",
        "        self.state = \"idle\"\n"
        "        self.clock = wallclock.Clock()",
    )
    assert rules_of(check(src)) == ["CONC301"]


def test_conc301_lock_recognized_through_import_alias():
    # an actual RLock bound via a from-import alias IS synchronization —
    # constructor recognition resolves canonical names like the other
    # rules, not literal spellings
    src = _THREADED.replace(
        "import threading",
        "import threading\nfrom threading import RLock as _RL",
    ).replace(
        "        self.state = s",
        "        with self._guard:\n            self.state = s",
    ).replace(
        "        while self.state != \"stop\":\n            pass",
        "        with self._guard:\n            s = self.state",
    ).replace(
        "        self.state = \"idle\"",
        "        self.state = \"idle\"\n        self._guard = _RL()",
    )
    assert not check(src)


def test_conc301_module_level_lock_recognized():
    src = _THREADED.replace(
        "import threading",
        "import threading\n_IO_LOCK = threading.Lock()",
    ).replace(
        "        self.state = s",
        "        with _IO_LOCK:\n            self.state = s",
    ).replace(
        "        while self.state != \"stop\":\n            pass",
        "        with _IO_LOCK:\n            s = self.state",
    )
    assert not check(src)


def test_conc301_timer_spawn_recognized():
    # threading.Timer runs its function on a new thread exactly like
    # Thread(target=...) — the pre-conclint rule missed it entirely
    src = _THREADED.replace(
        "threading.Thread(target=self._run, daemon=True)",
        "threading.Timer(5.0, self._run)")
    assert rules_of(check(src)) == ["CONC301"]
    # keyword form too
    src = _THREADED.replace(
        "threading.Thread(target=self._run, daemon=True)",
        "threading.Timer(interval=5.0, function=self._run)")
    assert rules_of(check(src)) == ["CONC301"]
    # aliased import cannot evade (canonical-name matching)
    src = ("from threading import Timer as _T\n"
           + _THREADED.replace(
               "threading.Thread(target=self._run, daemon=True)",
               "_T(5.0, self._run)"))
    assert rules_of(check(src)) == ["CONC301"]


def test_conc301_positional_thread_target_recognized():
    # Thread(group, target, ...): target is positional arg 1
    src = _THREADED.replace(
        "threading.Thread(target=self._run, daemon=True)",
        "threading.Thread(None, self._run)")
    assert rules_of(check(src)) == ["CONC301"]


def test_conc301_thread_subclass_run_is_a_target():
    src = ("import threading\n"
           "class W(threading.Thread):\n"
           "    def __init__(self):\n"
           "        super().__init__(daemon=True)\n"
           "        self.state = 'idle'\n"
           "    def poke(self, s):\n"
           "        self.state = s\n"
           "    def run(self):\n"
           "        while self.state != 'stop':\n"
           "            pass\n")
    hits = check(src)
    assert rules_of(hits) == ["CONC301"]
    assert "self.state" in hits[0].message
    # a non-Thread base with a run() method is NOT a thread body
    assert not check(src.replace("class W(threading.Thread):",
                                 "class W(Base):"))


def test_conc301_timer_subclass_fixture_golden_json():
    got = _json_report([str(FIXDIR / "timer_subclass.py")], str(FIXDIR))
    want = (FIXDIR / "timer_subclass.golden.json").read_text()
    assert got == want
    doc = json.loads(got)
    assert [f["rule"] for f in doc["findings"]] == ["CONC301"] * 2


_NODE_PY = "arbius_tpu/node/somefile.py"   # CONC302 is node/-scoped


def test_conc302_unbounded_queue_in_node_scope():
    src = "import queue\nq = queue.Queue()\n"
    hits = analyze_source(src, _NODE_PY)
    assert rules_of(hits) == ["CONC302"]
    assert "backpressure" in hits[0].message or \
        "unbounded" in hits[0].message
    # the same construct outside arbius_tpu/node/ is not a finding:
    # tools and tests may buffer freely
    assert not analyze_source(src, "tools/somefile.py")
    assert not analyze_source(src, "snippet.py")
    # outside enforce[]'d files the finding is baselineable like any
    # other (snippet-keyed, reason-mandatory)
    bl = baseline_mod.update(hits, None)
    assert len(bl.entries) == 1 and not bl.apply(hits)


def test_conc302_literal_zero_and_negative_are_unbounded():
    src = ("import queue\nfrom queue import LifoQueue\n"
           "a = queue.Queue(maxsize=0)\n"
           "b = LifoQueue(-1)\n"
           "c = queue.PriorityQueue(maxsize=None)\n")
    hits = analyze_source(src, _NODE_PY)
    assert rules_of(hits) == ["CONC302"] * 3


def test_conc302_bounded_and_dynamic_are_clean():
    assert not analyze_source(
        "import queue\n"
        "a = queue.Queue(maxsize=8)\n"
        "b = queue.Queue(4)\n"
        "c = queue.Queue(maxsize=max(1, depth))\n", _NODE_PY)


def test_conc302_fixture_golden_json():
    got = _json_report([str(FIXDIR / "arbius_tpu")], str(FIXDIR))
    want = (FIXDIR / "unbounded_queue.golden.json").read_text()
    assert got == want
    doc = json.loads(got)
    assert [f["rule"] for f in doc["findings"]] == ["CONC302"] * 4
    # the pragma'd construction in the fixture was absorbed by allow[]
    assert not any("allowed" in f["snippet"] for f in doc["findings"])


def test_conc302_enforced_in_pipeline_cannot_be_waived():
    """node/pipeline.py enforces CONC302: an unbounded queue added there
    is fatal even with a pragma, and the baseline refuses to absorb it."""
    src = (REPO / "arbius_tpu/node/pipeline.py").read_text()
    assert not analyze_source(src, "arbius_tpu/node/pipeline.py"), \
        "pipeline.py should be clean"
    evil = src + ("\n_overflow = queue.Queue()"
                  "  # detlint: allow[CONC302] nope\n")
    hits = analyze_source(evil, "arbius_tpu/node/pipeline.py")
    assert any(f.rule == "CONC302" and f.enforced for f in hits)
    assert not baseline_mod.update(hits, None).entries


# -- OBS501: metric-name ↔ doc drift ----------------------------------------

_OBS_PY = "arbius_tpu/obs/somefile.py"   # OBS501 is arbius_tpu/-scoped


def test_obs501_undocumented_metric_is_a_finding():
    src = ('obs.registry.counter("arbius_zz_rotting_total", "x").inc()\n'
           'obs.registry.counter("arbius_tasks_seen_total").inc()\n')
    hits = analyze_source(src, _OBS_PY)
    assert rules_of(hits) == ["OBS501"]
    assert "arbius_zz_rotting_total" in hits[0].message
    assert "docs/observability.md" in hits[0].message
    # outside the shipped tree (tools/tests) metrics are free
    assert not analyze_source(src, "tools/somefile.py")
    assert not analyze_source(src, "tests/somefile.py")


def test_obs501_skips_family_constructors_and_keywords():
    # f-string names are families whose members are documented rows;
    # a name= keyword literal IS checked
    src = ('reg.counter(f"arbius_{name}_total").inc()\n'
           'reg.gauge(name="arbius_zz_rotting_depth")\n'
           'reg.histogram("arbius_stage_seconds")\n')
    hits = analyze_source(src, _OBS_PY)
    assert rules_of(hits) == ["OBS501"]
    assert "arbius_zz_rotting_depth" in hits[0].message


def test_obs501_every_new_fleetscope_metric_is_documented():
    """The rule is live on the real tree: the fleetscope metrics this
    PR adds must each resolve against the doc (and the whole-package
    self-check below keeps the invariant for every future metric)."""
    from arbius_tpu.analysis.rules_obs import documented_metric_names

    documented = documented_metric_names()
    for name in ("arbius_fleet_queue_wait_seconds",
                 "arbius_fleet_time_to_commit_seconds",
                 "arbius_obs_sidecar_flushes_total"):
        assert name in documented, name


def test_obs501_fixture_golden_json():
    fixroot = FIXDIR / "obs501"
    got = _json_report([str(fixroot / "arbius_tpu")], str(fixroot))
    want = (FIXDIR / "obs501.golden.json").read_text()
    assert got == want
    doc = json.loads(got)
    assert [f["rule"] for f in doc["findings"]] == ["OBS501"] * 2
    # the pragma'd registration in the fixture was absorbed by allow[]
    assert not any("waived" in f["snippet"] for f in doc["findings"])


def test_obs501_doc_rot_fixture_golden_json():
    """The rot direction (doc → code): the fixture tree documents three
    names — a live literal, an f-string family member (absolved by the
    family honesty bound), and a ghost. Exactly the ghost flags,
    anchored on the DOC line, pinned byte-for-byte."""
    fixroot = FIXDIR / "obs501_rot"
    got = _json_report([str(fixroot / "arbius_tpu")], str(fixroot))
    want = (FIXDIR / "obs501_rot.golden.json").read_text()
    assert got == want
    doc = json.loads(got)
    (finding,) = doc["findings"]
    assert finding["rule"] == "OBS501"
    assert finding["path"] == "docs/observability.md"
    assert "arbius_fixture_ghost_depth" in finding["message"]
    assert "doc rot" in finding["message"]


def test_obs501_doc_rot_only_fires_on_whole_package_scans():
    """A single-file run sees only a slice of the tree — every doc row
    would look rotten. The rot direction requires a directory named
    arbius_tpu among the inputs."""
    from arbius_tpu.analysis.core import analyze_paths

    fixroot = FIXDIR / "obs501_rot"
    partial = analyze_paths(
        [str(fixroot / "arbius_tpu" / "metrics.py")], root=str(fixroot))
    assert not any(f.path.startswith("docs/") for f in partial)
    full = analyze_paths([str(fixroot / "arbius_tpu")],
                         root=str(fixroot))
    assert any(f.path == "docs/observability.md" for f in full)
    # a SUPERSET scan (the root containing arbius_tpu/) covers the
    # whole package too — the rot direction must not silently skip it
    superset = analyze_paths([str(fixroot)], root=str(fixroot))
    assert any(f.path == "docs/observability.md" for f in superset)


def test_obs501_doc_rot_respects_select():
    """--select gates the rot direction like any rule. (The real tree's
    cleanliness is already enforced by the tier-1 whole-tree self-check
    — a rot finding cannot be baselined away into it silently.)"""
    from arbius_tpu.analysis.core import analyze_paths

    fixroot = FIXDIR / "obs501_rot"
    rot = analyze_paths([str(fixroot / "arbius_tpu")],
                        root=str(fixroot), select={"OBS501"})
    assert [f.path for f in rot] == ["docs/observability.md"]
    assert not analyze_paths([str(fixroot / "arbius_tpu")],
                             root=str(fixroot), select={"DET101"})


def test_obs501_alert_rule_names_are_checked():
    """The alert direction (docs/healthwatch.md): a literal
    AlertRule(name=…) under arbius_tpu/ with no `alert="…"` row in
    docs/observability.md is OBS501, exactly like an undocumented
    metric; documented catalog names are clean."""
    src = ('ghost = AlertRule(name="zz_rotting_rule", summary="s",\n'
           '                  signal="g")\n'
           'ok = AlertRule(name="stuck_tick", summary="s",\n'
           '               signal="stuck")\n')
    hits = analyze_source(src, _OBS_PY)
    assert rules_of(hits) == ["OBS501"]
    assert "zz_rotting_rule" in hits[0].message
    assert "alert" in hits[0].message
    # outside the shipped tree, fixtures/tests build rules freely
    assert not analyze_source(src, "tests/somefile.py")


def test_obs501_every_catalog_rule_is_documented():
    """Live on the real tree: every shipped healthwatch rule id
    resolves against the doc's alert table (the whole-tree self-check
    keeps this for every future rule)."""
    from arbius_tpu.analysis.rules_obs import documented_alert_names
    from arbius_tpu.obs.healthwatch import RULE_NAMES

    documented = documented_alert_names()
    for name in RULE_NAMES:
        assert name in documented, name


def test_obs501_alerts_fixture_golden_json():
    """Both alert directions pinned byte-for-byte: the forward ghost
    (a catalog rule with no doc row; the waived twin absorbed by
    allow[]) and the rot direction (a documented alert whose rule
    vanished from the fixture tree — anchored on the DOC line)."""
    fixroot = FIXDIR / "obs501_alerts"
    got = _json_report([str(fixroot / "arbius_tpu")], str(fixroot))
    want = (FIXDIR / "obs501_alerts.golden.json").read_text()
    assert got == want
    doc = json.loads(got)
    assert [f["rule"] for f in doc["findings"]] == ["OBS501"] * 2
    paths = [f["path"] for f in doc["findings"]]
    assert paths == ["arbius_tpu/alerts.py", "docs/observability.md"]
    assert "fixture_ghost_rule" in doc["findings"][0]["message"]
    assert "fixture_rotten_rule" in doc["findings"][1]["message"]
    assert not any("fixture_waived_rule" in f["message"]
                   for f in doc["findings"])


# -- suppressions, enforce, LINT001 -----------------------------------------

def test_inline_suppression_same_line_and_above():
    assert not check(
        "import time\n"
        "t = time.time()  # detlint: allow[DET101] test clock\n")
    assert not check(
        "import time\n"
        "# detlint: allow[DET101] reason spanning\n"
        "# a second comment line\n"
        "t = time.time()\n")


def test_trailing_pragma_covers_wrapped_statement():
    # the finding anchors to the expression's FIRST line; a pragma at
    # the end of the wrapped statement must still reach it
    assert not check(
        "import time\n"
        "t = (time.\n"
        "     time())  # detlint: allow[DET101] test clock\n")


def test_pragma_covers_continuation_line_anchors():
    # a finding can anchor on a continuation line of a wrapped
    # statement; both pragma placements must still reach it
    assert not check(
        "import time\n"
        "# detlint: allow[DET101] wrapped call, nested anchor\n"
        "x = foo(\n"
        "    time.time())\n")
    assert not check(
        "import time\n"
        "x = foo(\n"
        "    time.time())  # detlint: allow[DET101] trailing on cont.\n")
    # and an own-line pragma inside a bracketed literal covers it too
    assert not check(
        "import time\n"
        "x = {\n"
        "    # detlint: allow[DET101] in-bracket pragma\n"
        "    'at': time.time(),\n"
        "}\n")


def test_unknown_rule_id_in_directive_is_lint002():
    hits = check("import time\n"
                 "t = time.time()  # detlint: allow[DET11] typo'd id\n")
    assert sorted(rules_of(hits)) == ["DET101", "LINT002"]
    # an enforce typo is flagged too — it must never silently void the
    # un-waivable guarantee
    hits = check("# detlint: enforce[DET1O1]\nx = 1\n")
    assert rules_of(hits) == ["LINT002"]


def test_suppression_without_reason_is_ignored_and_flagged():
    hits = check("import time\n"
                 "t = time.time()  # detlint: allow[DET101]\n")
    assert sorted(rules_of(hits)) == ["DET101", "LINT001"]


def test_suppression_is_rule_specific():
    hits = check("import time\n"
                 "t = time.time()  # detlint: allow[DET102] wrong rule\n")
    assert rules_of(hits) == ["DET101"]


def test_enforce_defeats_pragma_and_baseline():
    src = ("# detlint: enforce[DET101]\n"
           "import time\n"
           "t = time.time()  # detlint: allow[DET101] nice try\n")
    hits = analyze_source(src, "solverish.py")
    assert rules_of(hits) == ["DET101"] and hits[0].enforced
    bl = baseline_mod.update(hits, None)
    assert not bl.entries  # enforced findings are never baselined
    assert Baseline({}).apply(hits) == hits


def test_baseline_absorbs_by_snippet_not_line():
    src = "import time\nt = time.time()\n"
    hits = analyze_source(src, "f.py")
    bl = baseline_mod.update(hits, None)
    # shift the finding down two lines: same snippet, still absorbed
    moved = analyze_source("import time\n\n\nt = time.time()\n", "f.py")
    assert not bl.apply(moved)
    # a SECOND identical occurrence exceeds the count and fails
    twice = analyze_source(
        "import time\nt = time.time()\nt = time.time()\n", "f.py")
    assert len(bl.apply(twice)) == 1


# -- golden JSON + output determinism ---------------------------------------

def _json_report(paths, root):
    findings = analyze_paths(paths, root=root)
    return json.dumps(
        {"version": 1, "findings": [f.to_json() for f in findings]},
        indent=2, sort_keys=True) + "\n"


def test_multi_finding_golden_json():
    got = _json_report([str(FIXDIR / "multi_finding.py")], str(FIXDIR))
    want = (FIXDIR / "multi_finding.golden.json").read_text()
    assert got == want
    doc = json.loads(got)
    fams = {f["rule"][:3] for f in doc["findings"]}
    assert {"DET", "JIT"} <= fams and len(doc["findings"]) >= 7


def test_two_runs_byte_identical():
    a = _json_report([str(REPO / "arbius_tpu")], str(REPO))
    b = _json_report([str(REPO / "arbius_tpu")], str(REPO))
    assert a == b


# -- the tier-1 self-check (the actual guardrail) ---------------------------

def test_package_self_check_clean_against_baseline():
    findings = analyze_paths([str(REPO / "arbius_tpu")], root=str(REPO))
    bl = Baseline.load(str(REPO / "detlint-baseline.json"))
    residue = bl.apply(findings)
    assert residue == [], (
        "detlint found non-baselined findings — fix them, pragma them "
        "with a reason, or (if intentional) run tools/detlint.py "
        "--baseline-update and justify the new entries:\n"
        + "\n".join(f.text() for f in residue))


def test_baseline_entries_are_justified():
    doc = json.loads((REPO / "detlint-baseline.json").read_text())
    for e in doc["findings"]:
        assert e["reason"] and baseline_mod.UNREVIEWED not in e["reason"], \
            f"unjustified baseline entry: {e['path']} {e['rule']}"


def test_solve_path_files_declare_enforcement():
    # node/retry.py + node/solver.py must keep their enforce[] pragmas —
    # deleting the directive would let a future baseline absorb findings
    from arbius_tpu.analysis import parse_directives
    for rel, must in [
        ("arbius_tpu/node/solver.py",
         {"DET101", "DET102", "DET103", "DET104", "DET105"}),
        ("arbius_tpu/node/retry.py", {"DET101", "DET102", "DET105"}),
        ("arbius_tpu/node/pipeline.py", {"CONC302"}),
    ]:
        d = parse_directives((REPO / rel).read_text())
        assert d.enforced == must, f"{rel} enforce[] list drifted"


def test_injected_wall_clock_in_solver_is_caught(tmp_path):
    """Rule-rot regression (ISSUE satellite): a synthetic time.time()
    dropped into the real solver module must produce an ENFORCED DET101
    that neither pragma nor baseline can absorb."""
    src = (REPO / "arbius_tpu/node/solver.py").read_text()
    assert not analyze_source(src, "solver.py"), "solver should be clean"
    evil = src + ("\n\ndef _drift():\n"
                  "    import time\n"
                  "    return time.time()  # detlint: allow[DET101] no\n")
    hits = analyze_source(evil, "solver.py")
    assert any(f.rule == "DET101" and f.enforced for f in hits)
    assert not baseline_mod.update(hits, None).entries


def test_injected_rng_in_retry_is_caught():
    src = (REPO / "arbius_tpu/node/retry.py").read_text()
    evil = src + ("\n\ndef _jitter(delay):\n"
                  "    import random\n"
                  "    return delay * random.random()\n")
    hits = analyze_source(evil, "retry.py")
    assert any(f.rule == "DET102" and f.enforced for f in hits)


# -- CLI exit codes + baseline update determinism ---------------------------

def test_cli_exit_codes(tmp_path, capsys):
    clean = tmp_path / "clean.py"
    clean.write_text("x = 1\n")
    dirty = tmp_path / "dirty.py"
    dirty.write_text("import time\nt = time.time()\n")
    bl = str(tmp_path / "bl.json")
    assert cli_main([str(clean), "--baseline", bl]) == 0
    assert cli_main([str(dirty), "--baseline", bl]) == 1
    assert cli_main([str(dirty), "--select", "NOPE"]) == 2
    assert cli_main([str(tmp_path / "missing.py")]) == 2
    # an explicitly named non-.py file is a usage error, not "clean"
    notpy = tmp_path / "script"
    notpy.write_text("x = 1\n")
    assert cli_main([str(notpy)]) == 2
    assert cli_main(["--help"]) == 0
    capsys.readouterr()


def test_cli_baseline_update_deterministic_and_reason_preserving(tmp_path):
    dirty = tmp_path / "dirty.py"
    dirty.write_text("import time\nt = time.time()\n")
    bl = tmp_path / "bl.json"
    args = [str(dirty), "--root", str(tmp_path), "--baseline", str(bl),
            "--baseline-update"]
    assert cli_main(args) == 0
    doc = json.loads(bl.read_text())
    assert doc["findings"][0]["reason"] == baseline_mod.UNREVIEWED
    doc["findings"][0]["reason"] = "test clock, reviewed"
    bl.write_text(json.dumps(doc, indent=2, sort_keys=True) + "\n")
    first = bl.read_bytes()
    assert cli_main(args) == 0
    assert bl.read_bytes() == first  # reasons carried, bytes stable
    assert cli_main([str(dirty), "--root", str(tmp_path),
                     "--baseline", str(bl)]) == 0


def test_cli_baseline_update_refuses_select(tmp_path, capsys):
    f = tmp_path / "f.py"
    f.write_text("import time\nt = time.time()\n")
    rc = cli_main([str(f), "--select", "DET101", "--baseline-update",
                   "--baseline", str(tmp_path / "bl.json")])
    assert rc == 2
    assert not (tmp_path / "bl.json").exists()
    capsys.readouterr()


def test_cli_baseline_update_partial_paths_merge(tmp_path):
    a = tmp_path / "a.py"
    a.write_text("import time\nt = time.time()\n")
    b = tmp_path / "b.py"
    b.write_text("import random\nr = random.random()\n")
    bl = tmp_path / "bl.json"
    assert cli_main([str(a), str(b), "--root", str(tmp_path),
                     "--baseline", str(bl), "--baseline-update"]) == 0
    # a partial re-run over just a.py must keep b.py's reviewed entry
    assert cli_main([str(a), "--root", str(tmp_path),
                     "--baseline", str(bl), "--baseline-update"]) == 0
    doc = json.loads(bl.read_text())
    assert {e["path"] for e in doc["findings"]} == {"a.py", "b.py"}
    # and a fixed file's entries DO drop out of a partial rescan
    a.write_text("t = 1\n")
    assert cli_main([str(a), "--root", str(tmp_path),
                     "--baseline", str(bl), "--baseline-update"]) == 0
    doc = json.loads(bl.read_text())
    assert {e["path"] for e in doc["findings"]} == {"b.py"}


def test_cli_unreadable_file_is_usage_error(tmp_path, capsys):
    bad = tmp_path / "bad.py"
    bad.write_bytes(b"# -*- coding: latin-1 -*-\nx = '\xe9'\n# \xff\xfe\n")
    # PEP 263 coding declarations are honored (tokenize.open) — this
    # file is valid latin-1 Python and must analyze, not crash
    assert cli_main([str(bad), "--root", str(tmp_path),
                     "--baseline", str(tmp_path / "bl.json")]) == 0
    truly_bad = tmp_path / "broken.py"
    truly_bad.write_bytes(b"x = 1\n\xff\xfe\n")  # undeclared, not utf-8
    rc = cli_main([str(truly_bad), "--root", str(tmp_path),
                   "--baseline", str(tmp_path / "bl.json")])
    assert rc == 2  # tool failure is the usage exit, never "findings"
    capsys.readouterr()


def test_cli_json_output_is_sorted(tmp_path, capsys):
    f = tmp_path / "f.py"
    f.write_text("import time\nimport random\n"
                 "t = time.time()\nr = random.random()\n")
    rc = cli_main([str(f), "--root", str(tmp_path), "--json",
                   "--baseline", str(tmp_path / "none.json")])
    assert rc == 1
    doc = json.loads(capsys.readouterr().out)
    keys = [(x["path"], x["line"], x["col"], x["rule"])
            for x in doc["findings"]]
    assert keys == sorted(keys)


# -- tools layer ------------------------------------------------------------

def test_tools_share_arg_output_helper(tmp_path, capsys, monkeypatch):
    import _common
    import obs_dump

    import detlint as detlint_tool

    # obs_dump's metrics view is the shared table
    assert obs_dump.render_metrics({"b": 2, "a": 1.5}) == \
        _common.kv_table({"b": 2, "a": 1.5}) == "a  1.5\nb  2"
    # the detlint tool runs the same collect() pipeline with the same
    # exit-code contract
    clean = tmp_path / "ok.py"
    clean.write_text("x = 1\n")
    assert detlint_tool.main([str(clean),
                              "--baseline",
                              str(tmp_path / "bl.json")]) == 0
    dirty = tmp_path / "bad.py"
    dirty.write_text("import time\nt = time.time()\n")
    assert detlint_tool.main([str(dirty),
                              "--baseline",
                              str(tmp_path / "bl.json")]) == 1
    err = capsys.readouterr().err
    assert "findings by rule" in err and "DET101" in err


def test_module_entrypoint_runs():
    env = dict(os.environ, PYTHONPATH=str(REPO))
    out = subprocess.run(
        [sys.executable, "-m", "arbius_tpu.analysis",
         str(REPO / "arbius_tpu"), "--root", str(REPO),
         "--baseline", str(REPO / "detlint-baseline.json")],
        capture_output=True, text=True, env=env, cwd=str(REPO))
    assert out.returncode == 0, out.stdout + out.stderr
