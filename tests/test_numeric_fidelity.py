"""Numeric converter fidelity: converted torch weights must produce the
SAME numbers through the flax modules as through the torch originals.

The shape/bijectivity suites (test_convert*.py) prove every leaf lands in
the right slot with the right shape — but a silently transposed square
kernel or a swapped GEGLU half would pass them and only surface as a
wrong golden CID at deployment. These tests close that hole with what the
environment ships (torch + transformers; no diffusers/network needed):

  - random-init transformers `CLIPTextModel` / `CLIPTextModelWithProjection`
    built from a small config → state_dict → `convert_sd15_text` (+
    `convert_kandinsky2_text_projection`) → flax forward ≡ torch forward
    (the sd15 AND kandinsky text towers — reference capability:
    cog containers wrap exactly these towers).
  - hand-built torch replicas of the diffusers GEGLU fusion, attention
    projection layout, and ResnetBlock2D semantics → the corresponding
    low-level transforms (`_linear`, `_conv`, `_geglu_*`) → flax blocks.

Everything runs float32 on CPU; tolerances are a few ULP-decades above
f32 accumulation noise — a transposed weight blows them up by orders of
magnitude.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import pytest
import torch

from arbius_tpu.models.common import GEGLU, Attention, ResnetBlock
from arbius_tpu.models.sd15.convert import (
    _conv,
    _geglu_gate,
    _geglu_gate_b,
    _geglu_val,
    _geglu_val_b,
    _linear,
    convert_sd15_text,
)
from arbius_tpu.models.sd15.text_encoder import TextEncoder, TextEncoderConfig

pytestmark = [pytest.mark.slow, pytest.mark.model]

ATOL = RTOL = 2e-4  # f32 accumulation noise ceiling; transposes give O(1)


def _clip_config(act: str):
    from transformers import CLIPTextConfig

    # eos_token_id must NOT be 2: transformers keeps a legacy pooling
    # branch for eos==2 (pools at input_ids.argmax(), pre-4.24 bug
    # compatibility) — real CLIP towers ship eos=49407 (the max id)
    return CLIPTextConfig(
        vocab_size=96, hidden_size=32, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4,
        max_position_embeddings=16, hidden_act=act,
        projection_dim=24, eos_token_id=95, bos_token_id=1)


def _flax_text_config(act: str) -> TextEncoderConfig:
    return TextEncoderConfig(vocab_size=96, max_length=16, width=32,
                             layers=2, heads=4, act=act, dtype="float32")


def _ids(batch: int = 2) -> np.ndarray:
    """Token ids shaped like real prompts: BOS, tokens, first EOS, pad."""
    rng = np.random.default_rng(0)
    ids = rng.integers(3, 95, (batch, 16))
    ids[:, 0] = 1
    ids[0, 10:] = 95  # row 0: EOS at 10
    ids[1, 5:] = 95   # row 1: EOS at 5
    return ids.astype(np.int64)


def _converted_text_params(tm, act: str):
    sd = {k: v.detach().numpy() for k, v in tm.state_dict().items()}
    cfg = _flax_text_config(act)
    enc = TextEncoder(cfg)
    tmpl = jax.eval_shape(
        lambda: enc.init(jax.random.PRNGKey(0),
                         jnp.zeros((1, 16), jnp.int32)))["params"]
    params = convert_sd15_text(sd, tmpl, heads=cfg.heads,
                               head_dim=cfg.width // cfg.heads)
    return enc, params


@pytest.mark.parametrize("act", ["quick_gelu", "gelu"])
def test_text_tower_matches_torch_clip(act):
    """convert_sd15_text: flax last_hidden_state ≡ torch CLIPTextModel.

    quick_gelu is the SD-1.5 ViT-L tower; gelu is the open_clip-style
    tower the kandinsky/video text encoders use."""
    from transformers import CLIPTextModel

    torch.manual_seed(0)
    tm = CLIPTextModel(_clip_config(act)).eval()
    enc, params = _converted_text_params(tm, act)
    ids = _ids()
    with torch.no_grad():
        theirs = tm(input_ids=torch.from_numpy(ids)).last_hidden_state.numpy()
    ours = np.asarray(enc.apply({"params": params}, jnp.asarray(ids)))
    np.testing.assert_allclose(ours, theirs, atol=ATOL, rtol=RTOL)


def test_kandinsky_text_projection_matches_torch():
    """The kandinsky tower pair: CLIPTextModelWithProjection state dict →
    convert_sd15_text + convert_kandinsky2_text_projection; the flax
    EOT-pooled projected embedding ≡ torch `text_embeds` (the prior's
    conditioning input — models/kandinsky2/pipeline.py first_eos path)."""
    from transformers import CLIPTextModelWithProjection

    from arbius_tpu.models.kandinsky2.convert import (
        convert_kandinsky2_text_projection as convert_proj,
    )
    from arbius_tpu.models.kandinsky2.pipeline import TextProjection

    torch.manual_seed(1)
    tm = CLIPTextModelWithProjection(_clip_config("gelu")).eval()
    enc, params = _converted_text_params(tm, "gelu")
    sd = {k: v.detach().numpy() for k, v in tm.state_dict().items()}
    proj_mod = TextProjection(24)
    proj_tmpl = jax.eval_shape(
        lambda: proj_mod.init(jax.random.PRNGKey(0),
                              jnp.zeros((1, 32))))["params"]
    proj_params = convert_proj(sd, proj_tmpl)

    ids = _ids()
    with torch.no_grad():
        out = tm(input_ids=torch.from_numpy(ids))
    states = np.asarray(enc.apply({"params": params}, jnp.asarray(ids)))
    first_eos = np.argmax(ids == 95, axis=1)
    pooled = states[np.arange(ids.shape[0]), first_eos]
    ours = np.asarray(proj_mod.apply({"params": proj_params},
                                     jnp.asarray(pooled)))
    np.testing.assert_allclose(ours, out.text_embeds.numpy(),
                               atol=ATOL, rtol=RTOL)
    np.testing.assert_allclose(states, out.last_hidden_state.numpy(),
                               atol=ATOL, rtol=RTOL)


def test_geglu_split_matches_diffusers_fusion():
    """diffusers fuses GEGLU as one [2·inner, dim] projection chunked into
    (value, gate); the converter splits it into ff_val/ff_gate. The flax
    GEGLU over the split halves must equal `val * gelu_exact(gate)` over
    the fused torch projection."""
    torch.manual_seed(2)
    dim, inner = 12, 48
    proj = torch.nn.Linear(dim, 2 * inner)
    x = torch.randn(3, 5, dim)
    with torch.no_grad():
        val, gate = proj(x).chunk(2, dim=-1)
        theirs = (val * torch.nn.functional.gelu(gate)).numpy()

    w = proj.weight.detach().numpy()
    b = proj.bias.detach().numpy()
    params = {
        "ff_val": {"kernel": _geglu_val(w), "bias": _geglu_val_b(b)},
        "ff_gate": {"kernel": _geglu_gate(w), "bias": _geglu_gate_b(b)},
    }
    ours = np.asarray(GEGLU(inner, jnp.float32).apply(
        {"params": params}, jnp.asarray(x.numpy())))
    np.testing.assert_allclose(ours, theirs, atol=ATOL, rtol=RTOL)


def test_attention_matches_torch_sdpa():
    """The diffusers Attention projection layout (to_q/k/v bias-free,
    to_out.0 with bias) through `_linear` ≡ torch scaled_dot_product
    attention with the same projections."""
    torch.manual_seed(3)
    dim, heads, head_dim, S, Sk = 16, 4, 4, 6, 9
    to_q = torch.nn.Linear(dim, dim, bias=False)
    to_k = torch.nn.Linear(dim, dim, bias=False)
    to_v = torch.nn.Linear(dim, dim, bias=False)
    to_out = torch.nn.Linear(dim, dim)
    x = torch.randn(2, S, dim)
    ctx = torch.randn(2, Sk, dim)
    with torch.no_grad():
        def split(t):
            b, s, _ = t.shape
            return t.reshape(b, s, heads, head_dim).transpose(1, 2)

        q, k, v = split(to_q(x)), split(to_k(ctx)), split(to_v(ctx))
        o = torch.nn.functional.scaled_dot_product_attention(q, k, v)
        o = o.transpose(1, 2).reshape(2, S, dim)
        theirs = to_out(o).numpy()

    params = {
        "to_q": {"kernel": _linear(to_q.weight.detach().numpy())},
        "to_k": {"kernel": _linear(to_k.weight.detach().numpy())},
        "to_v": {"kernel": _linear(to_v.weight.detach().numpy())},
        "to_out": {"kernel": _linear(to_out.weight.detach().numpy()),
                   "bias": to_out.bias.detach().numpy()},
    }
    ours = np.asarray(Attention(heads, head_dim, jnp.float32).apply(
        {"params": params}, jnp.asarray(x.numpy()),
        context=jnp.asarray(ctx.numpy())))
    np.testing.assert_allclose(ours, theirs, atol=ATOL, rtol=RTOL)


class _TorchResnet(torch.nn.Module):
    """diffusers ResnetBlock2D semantics (default config): norm1→silu→
    conv1→(+time_emb)→norm2→silu→conv2, 1×1 conv shortcut on channel
    change."""

    def __init__(self, cin: int, cout: int, temb_dim: int):
        super().__init__()
        # GroupNorm32 uses gcd(C, 32) groups; mirror that per-norm
        self.norm1 = torch.nn.GroupNorm(int(np.gcd(cin, 32)), cin, eps=1e-5)
        self.conv1 = torch.nn.Conv2d(cin, cout, 3, padding=1)
        self.time_emb_proj = torch.nn.Linear(temb_dim, cout)
        self.norm2 = torch.nn.GroupNorm(int(np.gcd(cout, 32)), cout, eps=1e-5)
        self.conv2 = torch.nn.Conv2d(cout, cout, 3, padding=1)
        self.conv_shortcut = (torch.nn.Conv2d(cin, cout, 1)
                              if cin != cout else None)

    def forward(self, x, temb):
        h = torch.nn.functional.silu(self.norm1(x))
        h = self.conv1(h)
        h = h + self.time_emb_proj(torch.nn.functional.silu(temb))[:, :, None, None]
        h = torch.nn.functional.silu(self.norm2(h))
        h = self.conv2(h)
        skip = x if self.conv_shortcut is None else self.conv_shortcut(x)
        return skip + h


def test_resnet_block_matches_torch_reference():
    """_conv/_linear through the resnet leaf table ≡ the published
    ResnetBlock2D forward (channel-changing variant exercises skip_proj)."""
    torch.manual_seed(4)
    cin, cout, temb_dim = 8, 16, 20
    tm = _TorchResnet(cin, cout, temb_dim).eval()
    x = torch.randn(2, cin, 10, 10)
    temb = torch.randn(2, temb_dim)
    with torch.no_grad():
        theirs = tm(x, temb).numpy()

    g = lambda t: t.detach().numpy()
    params = {
        "GroupNorm32_0": {"GroupNorm_0": {"scale": g(tm.norm1.weight),
                                          "bias": g(tm.norm1.bias)}},
        "Conv_0": {"kernel": _conv(g(tm.conv1.weight)),
                   "bias": g(tm.conv1.bias)},
        "Dense_0": {"kernel": _linear(g(tm.time_emb_proj.weight)),
                    "bias": g(tm.time_emb_proj.bias)},
        "GroupNorm32_1": {"GroupNorm_0": {"scale": g(tm.norm2.weight),
                                          "bias": g(tm.norm2.bias)}},
        "Conv_1": {"kernel": _conv(g(tm.conv2.weight)),
                   "bias": g(tm.conv2.bias)},
        "skip_proj": {"kernel": _conv(g(tm.conv_shortcut.weight)),
                      "bias": g(tm.conv_shortcut.bias)},
    }
    x_nhwc = jnp.asarray(x.numpy().transpose(0, 2, 3, 1))
    ours = np.asarray(ResnetBlock(cout, jnp.float32).apply(
        {"params": params}, x_nhwc, jnp.asarray(temb.numpy())))
    np.testing.assert_allclose(ours.transpose(0, 3, 1, 2), theirs,
                               atol=ATOL, rtol=RTOL)


class _TorchSpatialNorm(torch.nn.Module):
    """diffusers SpatialNorm semantics (the MOVQ norm): GroupNorm(f)
    modulated by 1x1-conv scale/shift predicted from the nearest-upsampled
    quantized latent."""

    def __init__(self, f_channels: int, zq_channels: int):
        super().__init__()
        self.norm_layer = torch.nn.GroupNorm(int(np.gcd(f_channels, 32)),
                                             f_channels, eps=1e-6)
        self.conv_y = torch.nn.Conv2d(zq_channels, f_channels, 1)
        self.conv_b = torch.nn.Conv2d(zq_channels, f_channels, 1)

    def forward(self, f, zq):
        zq = torch.nn.functional.interpolate(zq, size=f.shape[-2:],
                                             mode="nearest")
        return self.norm_layer(f) * self.conv_y(zq) + self.conv_b(zq)


def test_movq_spatial_norm_matches_torch_reference():
    """The MOVQ decoder's SpatialNorm through the converter's leaf table
    (_spatial_norm_leaves transforms) ≡ the published formula."""
    from arbius_tpu.models.kandinsky2.movq import SpatialNorm

    torch.manual_seed(5)
    cf, cz = 8, 4
    tm = _TorchSpatialNorm(cf, cz).eval()
    f = torch.randn(2, cf, 8, 8)
    zq = torch.randn(2, cz, 4, 4)   # exercises the nearest upsample
    with torch.no_grad():
        theirs = tm(f, zq).numpy()

    # drive the ACTUAL converter leaf table: flax path -> (published key,
    # transform) — a same-shape swap in the table must fail this test
    from arbius_tpu.models.kandinsky2.convert import _spatial_norm_leaves

    sd = {k: v.detach().numpy() for k, v in tm.state_dict().items()}
    params = {}
    for path in ("norm/GroupNorm_0/scale", "norm/GroupNorm_0/bias",
                 "conv_y/kernel", "conv_y/bias",
                 "conv_b/kernel", "conv_b/bias"):
        key, tf = _spatial_norm_leaves(path)
        node = params
        parts = path.split("/")
        for part in parts[:-1]:
            node = node.setdefault(part, {})
        node[parts[-1]] = tf(sd[key])
    ours = np.asarray(SpatialNorm(jnp.float32).apply(
        {"params": params},
        jnp.asarray(f.numpy().transpose(0, 2, 3, 1)),
        jnp.asarray(zq.numpy().transpose(0, 2, 3, 1))))
    np.testing.assert_allclose(ours.transpose(0, 3, 1, 2), theirs,
                               atol=ATOL, rtol=RTOL)


def test_temporal_conv3d_transform_matches_torch():
    """The video converter's _tconv3d: a torch Conv3d with (3,1,1) kernel
    over [B, C, T, H, W] ≡ our frame-axis (3,) conv over [B, H, W, T, C]
    with the transformed kernel — the TemporalConvLayer hot path."""
    from arbius_tpu.models.video.convert import _tconv3d

    torch.manual_seed(6)
    ci, co, T, H, W = 4, 6, 5, 3, 3
    tc = torch.nn.Conv3d(ci, co, (3, 1, 1), padding=(1, 0, 0))
    x = torch.randn(2, ci, T, H, W)
    with torch.no_grad():
        theirs = tc(x).numpy()  # [B, co, T, H, W]

    import flax.linen as nn

    conv = nn.Conv(co, (3,), padding=[(1, 1)], dtype=jnp.float32)
    params = {"kernel": _tconv3d(tc.weight.detach().numpy()),
              "bias": tc.bias.detach().numpy()}
    # [B, C, T, H, W] -> [B, H, W, T, C] (the layout TemporalConvLayer
    # convolves in), back after
    x_f = jnp.asarray(x.numpy().transpose(0, 3, 4, 2, 1))
    ours = np.asarray(conv.apply({"params": params}, x_f))
    np.testing.assert_allclose(ours.transpose(0, 4, 3, 1, 2), theirs,
                               atol=ATOL, rtol=RTOL)


class _TorchAttention(torch.nn.Module):
    """diffusers Attention: to_q/k/v (no bias) + to_out.0 (bias)."""

    def __init__(self, dim: int, ctx_dim: int, heads: int):
        super().__init__()
        self.heads = heads
        self.to_q = torch.nn.Linear(dim, dim, bias=False)
        self.to_k = torch.nn.Linear(ctx_dim, dim, bias=False)
        self.to_v = torch.nn.Linear(ctx_dim, dim, bias=False)
        self.to_out = torch.nn.Linear(dim, dim)

    def forward(self, x, ctx=None):
        ctx = x if ctx is None else ctx
        b, s, d = x.shape
        hd = d // self.heads
        split = lambda t: t.view(b, -1, self.heads, hd).transpose(1, 2)
        o = torch.nn.functional.scaled_dot_product_attention(
            split(self.to_q(x)), split(self.to_k(ctx)), split(self.to_v(ctx)))
        return self.to_out(o.transpose(1, 2).reshape(b, s, d))


class _TorchTransformer2D(torch.nn.Module):
    """diffusers Transformer2DModel (depth 1): GN(1e-6) → proj_in 1×1 →
    [LN→self-attn, LN→cross-attn, LN→GEGLU FF, residual] → proj_out 1×1
    → +residual. The full SD-1.5 attention block at published structure."""

    def __init__(self, c: int, heads: int, ctx_dim: int):
        super().__init__()
        self.norm = torch.nn.GroupNorm(int(np.gcd(c, 32)), c, eps=1e-6)
        self.proj_in = torch.nn.Conv2d(c, c, 1)
        self.norm1 = torch.nn.LayerNorm(c, eps=1e-5)
        self.attn1 = _TorchAttention(c, c, heads)
        self.norm2 = torch.nn.LayerNorm(c, eps=1e-5)
        self.attn2 = _TorchAttention(c, ctx_dim, heads)
        self.norm3 = torch.nn.LayerNorm(c, eps=1e-5)
        self.ff_proj = torch.nn.Linear(c, 8 * c)   # fused GEGLU value|gate
        self.ff_out = torch.nn.Linear(4 * c, c)
        self.proj_out = torch.nn.Conv2d(c, c, 1)

    def forward(self, x, ctx):
        b, c, hh, ww = x.shape
        res = x
        h = self.proj_in(self.norm(x))
        h = h.flatten(2).transpose(1, 2)           # [B, HW, C]
        h = h + self.attn1(self.norm1(h))
        h = h + self.attn2(self.norm2(h), ctx)
        val, gate = self.ff_proj(self.norm3(h)).chunk(2, dim=-1)
        h = h + self.ff_out(val * torch.nn.functional.gelu(gate))
        h = h.transpose(1, 2).view(b, c, hh, ww)
        return self.proj_out(h) + res


def test_spatial_transformer_block_matches_torch():
    """The FULL SpatialTransformer forward (VERDICT r4 ask #7:
    block-level fidelity) ≡ the hand-built Transformer2DModel replica,
    with the GEGLU fusion split exactly as the converter splits it."""
    from arbius_tpu.models.common import SpatialTransformer

    torch.manual_seed(10)
    c, heads, ctx_dim, hw = 8, 2, 12, 6
    tm = _TorchTransformer2D(c, heads, ctx_dim).eval()
    x = torch.randn(2, c, hw, hw)
    ctx = torch.randn(2, 7, ctx_dim)
    with torch.no_grad():
        theirs = tm(x, ctx).numpy()

    g = lambda t: t.detach().numpy()
    def attn_params(a):
        return {"to_q": {"kernel": _linear(g(a.to_q.weight))},
                "to_k": {"kernel": _linear(g(a.to_k.weight))},
                "to_v": {"kernel": _linear(g(a.to_v.weight))},
                "to_out": {"kernel": _linear(g(a.to_out.weight)),
                           "bias": g(a.to_out.bias)}}
    ff_w = g(tm.ff_proj.weight)
    ff_b = g(tm.ff_proj.bias)
    params = {
        "GroupNorm32_0": {"GroupNorm_0": {"scale": g(tm.norm.weight),
                                          "bias": g(tm.norm.bias)}},
        "proj_in": {"kernel": _conv(g(tm.proj_in.weight)),
                    "bias": g(tm.proj_in.bias)},
        "block_0": {
            "LayerNorm_0": {"scale": g(tm.norm1.weight),
                            "bias": g(tm.norm1.bias)},
            "attn1": attn_params(tm.attn1),
            "LayerNorm_1": {"scale": g(tm.norm2.weight),
                            "bias": g(tm.norm2.bias)},
            "attn2": attn_params(tm.attn2),
            "LayerNorm_2": {"scale": g(tm.norm3.weight),
                            "bias": g(tm.norm3.bias)},
            "ff": {"ff_val": {"kernel": _linear(ff_w[:4 * c]),
                              "bias": ff_b[:4 * c]},
                   "ff_gate": {"kernel": _linear(ff_w[4 * c:]),
                               "bias": ff_b[4 * c:]}},
            "ff_out": {"kernel": _linear(g(tm.ff_out.weight)),
                       "bias": g(tm.ff_out.bias)},
        },
        "proj_out": {"kernel": _conv(g(tm.proj_out.weight)),
                     "bias": g(tm.proj_out.bias)},
    }
    ours = np.asarray(SpatialTransformer(heads, c // heads, depth=1,
                                         dtype=jnp.float32).apply(
        {"params": params},
        jnp.asarray(x.numpy().transpose(0, 2, 3, 1)),
        context=jnp.asarray(ctx.numpy())))
    np.testing.assert_allclose(ours.transpose(0, 3, 1, 2), theirs,
                               atol=ATOL, rtol=RTOL)


class _TorchTemporalConvLayer(torch.nn.Module):
    """diffusers TemporalConvLayer: four GN+SiLU+Conv3d((3,1,1)) stages,
    residual."""

    def __init__(self, c: int):
        super().__init__()
        for i in range(1, 5):
            setattr(self, f"norm{i}",
                    torch.nn.GroupNorm(int(np.gcd(c, 32)), c, eps=1e-5))
            setattr(self, f"conv{i}",
                    torch.nn.Conv3d(c, c, (3, 1, 1), padding=(1, 0, 0)))

    def forward(self, x):  # [B, C, T, H, W]
        h = x
        for i in range(1, 5):
            h = getattr(self, f"norm{i}")(h)
            h = getattr(self, f"conv{i}")(torch.nn.functional.silu(h))
        return x + h


def test_unet3d_temporal_conv_layer_matches_torch():
    """The FULL TemporalConvLayer forward (UNet3D's temporal mixing hot
    path) ≡ the published four-stage Conv3d replica, through the video
    converter's _tconv3d kernel transform."""
    from arbius_tpu.models.video.convert import _tconv3d
    from arbius_tpu.models.video.unet3d import TemporalConvLayer

    torch.manual_seed(11)
    c, T, hw = 8, 5, 4
    tm = _TorchTemporalConvLayer(c).eval()
    x = torch.randn(2, c, T, hw, hw)
    with torch.no_grad():
        theirs = tm(x).numpy()

    g = lambda t: t.detach().numpy()
    params = {}
    for i in range(1, 5):
        norm = getattr(tm, f"norm{i}")
        conv = getattr(tm, f"conv{i}")
        params[f"conv{i}_norm"] = {"GroupNorm_0": {"scale": g(norm.weight),
                                                   "bias": g(norm.bias)}}
        params[f"conv{i}"] = {"kernel": _tconv3d(g(conv.weight)),
                              "bias": g(conv.bias)}
    # [B, C, T, H, W] -> [B, T, H, W, C]
    ours = np.asarray(TemporalConvLayer(c, dtype=jnp.float32).apply(
        {"params": params}, jnp.asarray(x.numpy().transpose(0, 2, 3, 4, 1))))
    np.testing.assert_allclose(ours.transpose(0, 4, 1, 2, 3), theirs,
                               atol=ATOL, rtol=RTOL)


class _TorchConvGRU(torch.nn.Module):
    """Published RVM ConvGRU."""

    def __init__(self, c: int):
        super().__init__()
        self.ih = torch.nn.Conv2d(2 * c, 2 * c, 3, padding=1)
        self.hh = torch.nn.Conv2d(2 * c, c, 3, padding=1)

    def forward(self, x, h):
        r, z = self.ih(torch.cat([x, h], 1)).sigmoid().chunk(2, dim=1)
        c = self.hh(torch.cat([x, r * h], 1)).tanh()
        return (1 - z) * h + z * c


class _TorchUpsamplingBlock(torch.nn.Module):
    """Published RVM UpsamplingBlock: bilinear ×2 → crop → concat
    [x|skip|src] → conv(bias=False)+BN+ReLU → ConvGRU over half."""

    def __init__(self, cin: int, cskip: int, csrc: int, cout: int):
        super().__init__()
        self.conv = torch.nn.Conv2d(cin + cskip + csrc, cout, 3,
                                    padding=1, bias=False)
        self.bn = torch.nn.BatchNorm2d(cout)
        self.gru = _TorchConvGRU(cout // 2)

    def forward(self, x, f, s, r):
        x = torch.nn.functional.interpolate(
            x, scale_factor=2, mode="bilinear", align_corners=False)
        x = x[:, :, :s.shape[2], :s.shape[3]]
        x = torch.relu(self.bn(self.conv(torch.cat([x, f, s], 1))))
        a, b = x.chunk(2, dim=1)
        b = self.gru(b, r)
        return torch.cat([a, b], 1), b


def test_rvm_upsampling_block_matches_torch():
    """A FULL RVM decoder stage (UpsamplingBlock incl. ConvGRU state
    update and inference-form BN) ≡ the published torch forward."""
    from arbius_tpu.models.rvm.model import UpsamplingBlock

    torch.manual_seed(12)
    cin, cskip, csrc, cout = 6, 4, 3, 8
    tm = _TorchUpsamplingBlock(cin, cskip, csrc, cout).eval()
    # non-trivial running stats (eval-mode BN actually exercises them)
    tm.bn.running_mean.uniform_(-0.5, 0.5)
    tm.bn.running_var.uniform_(0.5, 1.5)
    x = torch.randn(2, cin, 4, 4)
    f = torch.randn(2, cskip, 8, 8)
    s = torch.randn(2, csrc, 8, 8)
    r = torch.randn(2, cout // 2, 8, 8)
    with torch.no_grad():
        theirs, rec = (t.numpy() for t in tm(x, f, s, r))

    g = lambda t: t.detach().numpy()
    params = {
        "conv": {"kernel": _conv(g(tm.conv.weight))},
        "bn": {"scale": g(tm.bn.weight), "bias": g(tm.bn.bias),
               "mean": g(tm.bn.running_mean), "var": g(tm.bn.running_var)},
        "gru": {"ih": {"kernel": _conv(g(tm.gru.ih.weight)),
                       "bias": g(tm.gru.ih.bias)},
                "hh": {"kernel": _conv(g(tm.gru.hh.weight)),
                       "bias": g(tm.gru.hh.bias)}},
    }
    nhwc = lambda t: jnp.asarray(t.numpy().transpose(0, 2, 3, 1))
    ours, rec_ours = UpsamplingBlock(cout, dtype=jnp.float32).apply(
        {"params": params}, nhwc(x), nhwc(f), nhwc(s), nhwc(r))
    np.testing.assert_allclose(np.asarray(ours).transpose(0, 3, 1, 2),
                               theirs, atol=ATOL, rtol=RTOL)
    np.testing.assert_allclose(np.asarray(rec_ours).transpose(0, 3, 1, 2),
                               rec, atol=ATOL, rtol=RTOL)


class _TorchVAEResnet(torch.nn.Module):
    """AutoencoderKL ResnetBlock2D: no time embedding, eps 1e-6."""

    def __init__(self, c: int):
        super().__init__()
        self.norm1 = torch.nn.GroupNorm(int(np.gcd(c, 32)), c, eps=1e-6)
        self.conv1 = torch.nn.Conv2d(c, c, 3, padding=1)
        self.norm2 = torch.nn.GroupNorm(int(np.gcd(c, 32)), c, eps=1e-6)
        self.conv2 = torch.nn.Conv2d(c, c, 3, padding=1)

    def forward(self, x):
        h = self.conv1(torch.nn.functional.silu(self.norm1(x)))
        h = self.conv2(torch.nn.functional.silu(self.norm2(h)))
        return x + h


class _TorchVAEDecoder(torch.nn.Module):
    """AutoencoderKL decoder at the flax tiny topology: post_quant 1×1 →
    conv_in → mid res/attn/res → 4 up levels (2 resnets + upsample) →
    GN+SiLU+conv_out."""

    def __init__(self, lat: int = 4, c: int = 8, levels: int = 4):
        super().__init__()
        self.levels = levels
        self.post_quant = torch.nn.Conv2d(lat, lat, 1)
        self.conv_in = torch.nn.Conv2d(lat, c, 3, padding=1)
        self.mid_res_0 = _TorchVAEResnet(c)
        self.attn_norm = torch.nn.GroupNorm(int(np.gcd(c, 32)), c, eps=1e-6)
        self.to_q = torch.nn.Linear(c, c)
        self.to_k = torch.nn.Linear(c, c)
        self.to_v = torch.nn.Linear(c, c)
        self.to_out = torch.nn.Linear(c, c)
        self.mid_res_1 = _TorchVAEResnet(c)
        for lv in range(levels):
            for j in range(2):
                setattr(self, f"up_{lv}_res_{j}", _TorchVAEResnet(c))
            if lv > 0:
                setattr(self, f"up_{lv}_us", torch.nn.Conv2d(c, c, 3,
                                                             padding=1))
        self.norm_out = torch.nn.GroupNorm(int(np.gcd(c, 32)), c, eps=1e-6)
        self.conv_out = torch.nn.Conv2d(c, 3, 3, padding=1)

    def forward(self, z):
        h = self.conv_in(self.post_quant(z))
        h = self.mid_res_0(h)
        b, c, hh, ww = h.shape
        a = self.attn_norm(h).flatten(2).transpose(1, 2)
        q, k, v = self.to_q(a), self.to_k(a), self.to_v(a)
        o = torch.nn.functional.scaled_dot_product_attention(
            q[:, None], k[:, None], v[:, None])[:, 0]  # single head
        h = h + self.to_out(o).transpose(1, 2).view(b, c, hh, ww)
        h = self.mid_res_1(h)
        for lv in reversed(range(self.levels)):
            for j in range(2):
                h = getattr(self, f"up_{lv}_res_{j}")(h)
            if lv > 0:
                h = torch.nn.functional.interpolate(h, scale_factor=2,
                                                    mode="nearest")
                h = getattr(self, f"up_{lv}_us")(h)
        return self.conv_out(torch.nn.functional.silu(self.norm_out(h)))


def test_vae_decoder_matches_torch():
    """The FULL VAEDecoder forward (latent → pixels, every sub-block) ≡
    the hand-built AutoencoderKL replica at the same topology."""
    from arbius_tpu.models.sd15.vae import VAEConfig, VAEDecoder

    torch.manual_seed(13)
    tm = _TorchVAEDecoder().eval()
    z = torch.randn(2, 4, 4, 4)
    with torch.no_grad():
        theirs = tm(z).numpy()

    g = lambda t: t.detach().numpy()
    def res_params(m):
        return {"GroupNorm32_0": {"GroupNorm_0": {"scale": g(m.norm1.weight),
                                                  "bias": g(m.norm1.bias)}},
                "Conv_0": {"kernel": _conv(g(m.conv1.weight)),
                           "bias": g(m.conv1.bias)},
                "GroupNorm32_1": {"GroupNorm_0": {"scale": g(m.norm2.weight),
                                                  "bias": g(m.norm2.bias)}},
                "Conv_1": {"kernel": _conv(g(m.conv2.weight)),
                           "bias": g(m.conv2.bias)}}
    lin = lambda m: {"kernel": _linear(g(m.weight)), "bias": g(m.bias)}
    params = {
        "post_quant": {"kernel": _conv(g(tm.post_quant.weight)),
                       "bias": g(tm.post_quant.bias)},
        "conv_in": {"kernel": _conv(g(tm.conv_in.weight)),
                    "bias": g(tm.conv_in.bias)},
        "mid_res_0": res_params(tm.mid_res_0),
        "mid_attn": {
            "GroupNorm32_0": {"GroupNorm_0": {"scale": g(tm.attn_norm.weight),
                                              "bias": g(tm.attn_norm.bias)}},
            "Attention_0": {"to_q": lin(tm.to_q), "to_k": lin(tm.to_k),
                            "to_v": lin(tm.to_v), "to_out": lin(tm.to_out)},
        },
        "mid_res_1": res_params(tm.mid_res_1),
        "norm_out": {"GroupNorm_0": {"scale": g(tm.norm_out.weight),
                                     "bias": g(tm.norm_out.bias)}},
        "conv_out": {"kernel": _conv(g(tm.conv_out.weight)),
                     "bias": g(tm.conv_out.bias)},
    }
    for lv in range(4):
        for j in range(2):
            params[f"up_{lv}_res_{j}"] = res_params(
                getattr(tm, f"up_{lv}_res_{j}"))
        if lv > 0:
            us = getattr(tm, f"up_{lv}_us")
            params[f"up_{lv}_us"] = {"Conv_0": {
                "kernel": _conv(g(us.weight)), "bias": g(us.bias)}}
    cfg = VAEConfig(block_channels=(8, 8, 8, 8), layers_per_block=1,
                    dtype="float32")
    ours = np.asarray(VAEDecoder(cfg).apply(
        {"params": params}, jnp.asarray(z.numpy().transpose(0, 2, 3, 1))))
    np.testing.assert_allclose(ours.transpose(0, 3, 1, 2), theirs,
                               atol=ATOL, rtol=RTOL)


class _TorchMOVQResBlock(torch.nn.Module):
    """MOVQ ResnetBlock2D variant: SpatialNorm conditioning on the raw
    latent instead of GroupNorm, 1×1 skip on channel change."""

    def __init__(self, cin: int, cout: int, cz: int):
        super().__init__()
        self.norm1 = _TorchSpatialNorm(cin, cz)
        self.conv1 = torch.nn.Conv2d(cin, cout, 3, padding=1)
        self.norm2 = _TorchSpatialNorm(cout, cz)
        self.conv2 = torch.nn.Conv2d(cout, cout, 3, padding=1)
        self.skip = (torch.nn.Conv2d(cin, cout, 1)
                     if cin != cout else None)

    def forward(self, x, z):
        h = self.conv1(torch.nn.functional.silu(self.norm1(x, z)))
        h = self.conv2(torch.nn.functional.silu(self.norm2(h, z)))
        return (x if self.skip is None else self.skip(x)) + h


def test_movq_decoder_stage_matches_torch():
    """A FULL MOVQ decoder stage — two SpatialNorm-conditioned resnets
    (one channel-changing) + nearest upsample conv — ≡ the published
    torch forward (VERDICT r4 ask #7: the 'SpatialNorm stack')."""
    from arbius_tpu.models.kandinsky2.movq import MOVQResBlock

    torch.manual_seed(14)
    cin, cout, cz = 12, 8, 4
    b1 = _TorchMOVQResBlock(cin, cout, cz).eval()
    b2 = _TorchMOVQResBlock(cout, cout, cz).eval()
    us = torch.nn.Conv2d(cout, cout, 3, padding=1)
    x = torch.randn(2, cin, 4, 4)
    z = torch.randn(2, cz, 2, 2)   # exercises the nearest upsample in SN
    with torch.no_grad():
        h = b2(b1(x, z), z)
        theirs = us(torch.nn.functional.interpolate(
            h, scale_factor=2, mode="nearest")).numpy()

    g = lambda t: t.detach().numpy()
    def sn_params(m):
        return {"norm": {"GroupNorm_0": {"scale": g(m.norm_layer.weight),
                                         "bias": g(m.norm_layer.bias)}},
                "conv_y": {"kernel": _conv(g(m.conv_y.weight)),
                           "bias": g(m.conv_y.bias)},
                "conv_b": {"kernel": _conv(g(m.conv_b.weight)),
                           "bias": g(m.conv_b.bias)}}
    def block_params(m, skip: bool):
        p = {"norm1": sn_params(m.norm1),
             "Conv_0": {"kernel": _conv(g(m.conv1.weight)),
                        "bias": g(m.conv1.bias)},
             "norm2": sn_params(m.norm2),
             "Conv_1": {"kernel": _conv(g(m.conv2.weight)),
                        "bias": g(m.conv2.bias)}}
        if skip:
            p["skip"] = {"kernel": _conv(g(m.skip.weight)),
                         "bias": g(m.skip.bias)}
        return p

    import flax.linen as fnn

    class Stage(fnn.Module):
        @fnn.compact
        def __call__(self, x, z):
            from arbius_tpu.models.common import Upsample
            h = MOVQResBlock(8, jnp.float32, name="b1")(x, z)
            h = MOVQResBlock(8, jnp.float32, name="b2")(h, z)
            return Upsample(8, jnp.float32, name="us")(h)

    params = {"b1": block_params(b1, True), "b2": block_params(b2, False),
              "us": {"Conv_0": {"kernel": _conv(g(us.weight)),
                                "bias": g(us.bias)}}}
    nhwc = lambda t: jnp.asarray(t.numpy().transpose(0, 2, 3, 1))
    ours = np.asarray(Stage().apply({"params": params}, nhwc(x), nhwc(z)))
    np.testing.assert_allclose(ours.transpose(0, 3, 1, 2), theirs,
                               atol=ATOL, rtol=RTOL)


class _TorchPriorBlock(torch.nn.Module):
    """Published prior block: pre-LN biased self-attention + exact-GELU
    MLP (diffusers BasicTransformerBlock, attention_bias=True,
    activation_fn='gelu', self-attention only)."""

    def __init__(self, dim: int, heads: int):
        super().__init__()
        self.heads = heads
        self.norm1 = torch.nn.LayerNorm(dim, eps=1e-5)
        self.to_q = torch.nn.Linear(dim, dim)
        self.to_k = torch.nn.Linear(dim, dim)
        self.to_v = torch.nn.Linear(dim, dim)
        self.to_out = torch.nn.Linear(dim, dim)
        self.norm3 = torch.nn.LayerNorm(dim, eps=1e-5)
        self.ff_in = torch.nn.Linear(dim, 4 * dim)
        self.ff_out = torch.nn.Linear(4 * dim, dim)

    def forward(self, x):
        b, s, d = x.shape
        hd = d // self.heads
        h = self.norm1(x)
        split = lambda t: t.view(b, s, self.heads, hd).transpose(1, 2)
        o = torch.nn.functional.scaled_dot_product_attention(
            split(self.to_q(h)), split(self.to_k(h)), split(self.to_v(h)))
        x = x + self.to_out(o.transpose(1, 2).reshape(b, s, d))
        h = self.norm3(x)
        return x + self.ff_out(torch.nn.functional.gelu(self.ff_in(h)))


def test_kandinsky_prior_block_matches_torch():
    """A FULL kandinsky prior transformer block ≡ the published biased-
    attention + exact-GELU forward."""
    from arbius_tpu.models.kandinsky2.prior import PriorBlock

    torch.manual_seed(15)
    dim, heads = 16, 4
    tm = _TorchPriorBlock(dim, heads).eval()
    x = torch.randn(2, 9, dim)
    with torch.no_grad():
        theirs = tm(x).numpy()

    g = lambda t: t.detach().numpy()
    lin = lambda m: {"kernel": _linear(g(m.weight)), "bias": g(m.bias)}
    params = {
        "norm1": {"scale": g(tm.norm1.weight), "bias": g(tm.norm1.bias)},
        "attn1": {"to_q": lin(tm.to_q), "to_k": lin(tm.to_k),
                  "to_v": lin(tm.to_v), "to_out": lin(tm.to_out)},
        "norm3": {"scale": g(tm.norm3.weight), "bias": g(tm.norm3.bias)},
        "ff_in": lin(tm.ff_in),
        "ff_out": lin(tm.ff_out),
    }
    ours = np.asarray(PriorBlock(heads, dim // heads, jnp.float32).apply(
        {"params": params}, jnp.asarray(x.numpy())))
    np.testing.assert_allclose(ours, theirs, atol=ATOL, rtol=RTOL)


class _TorchInvertedResidual(torch.nn.Module):
    """torchvision MobileNetV3 InvertedResidual with SE + hardswish, the
    RVM encoder's block class (expand 1x1 + depthwise + SE + project,
    BN eps 1e-3, residual on shape match)."""

    def __init__(self, cin: int, k: int, exp: int, cout: int):
        super().__init__()
        def bn(c):
            m = torch.nn.BatchNorm2d(c, eps=1e-3)
            m.running_mean.uniform_(-0.2, 0.2)
            m.running_var.uniform_(0.7, 1.3)
            return m
        self.expand = torch.nn.Conv2d(cin, exp, 1, bias=False)
        self.bn1 = bn(exp)
        self.dw = torch.nn.Conv2d(exp, exp, k, padding=(k - 1) // 2,
                                  groups=exp, bias=False)
        self.bn2 = bn(exp)
        sq = (exp // 4 + 4) // 8 * 8  # torchvision _make_divisible(exp/4)
        self.fc1 = torch.nn.Conv2d(exp, sq, 1)
        self.fc2 = torch.nn.Conv2d(sq, exp, 1)
        self.project = torch.nn.Conv2d(exp, cout, 1, bias=False)
        self.bn3 = bn(cout)
        self.res = cin == cout

    def forward(self, x):
        hs = torch.nn.functional.hardswish
        h = hs(self.bn1(self.expand(x)))
        h = hs(self.bn2(self.dw(h)))
        s = h.mean((2, 3), keepdim=True)
        s = torch.nn.functional.hardsigmoid(
            self.fc2(torch.relu(self.fc1(s))))
        h = h * s
        h = self.bn3(self.project(h))
        return x + h if self.res else h


def test_rvm_encoder_block_matches_torch():
    """A FULL MobileNetV3 InvertedResidual (expand+depthwise+SE+project,
    inference-form BN, hardswish/hardsigmoid) ≡ torchvision semantics —
    the RVM encoder-side counterpart of the decoder-stage test."""
    from arbius_tpu.models.rvm.model import InvertedResidual

    torch.manual_seed(16)
    cin, k, exp, cout = 8, 3, 24, 8
    tm = _TorchInvertedResidual(cin, k, exp, cout).eval()
    x = torch.randn(2, cin, 6, 6)
    with torch.no_grad():
        theirs = tm(x).numpy()

    g = lambda t: t.detach().numpy()
    def bn_params(m):
        return {"scale": g(m.weight), "bias": g(m.bias),
                "mean": g(m.running_mean), "var": g(m.running_var)}
    def dwconv(w):  # torch [C,1,k,k] grouped -> flax [k,k,1,C]
        return g(w).transpose(2, 3, 1, 0)
    params = {
        "expand": {"conv": {"kernel": _conv(g(tm.expand.weight))},
                   "bn": bn_params(tm.bn1)},
        "depthwise": {"conv": {"kernel": dwconv(tm.dw.weight)},
                      "bn": bn_params(tm.bn2)},
        "se": {"fc1": {"kernel": _conv(g(tm.fc1.weight)),
                       "bias": g(tm.fc1.bias)},
               "fc2": {"kernel": _conv(g(tm.fc2.weight)),
                       "bias": g(tm.fc2.bias)}},
        "project": {"conv": {"kernel": _conv(g(tm.project.weight))},
                    "bn": bn_params(tm.bn3)},
    }
    row = (cin, k, exp, cout, True, "hardswish", 1, 1)
    ours = np.asarray(InvertedResidual(row, jnp.float32).apply(
        {"params": params}, jnp.asarray(x.numpy().transpose(0, 2, 3, 1))))
    np.testing.assert_allclose(ours.transpose(0, 3, 1, 2), theirs,
                               atol=ATOL, rtol=RTOL)
