"""Numeric converter fidelity: converted torch weights must produce the
SAME numbers through the flax modules as through the torch originals.

The shape/bijectivity suites (test_convert*.py) prove every leaf lands in
the right slot with the right shape — but a silently transposed square
kernel or a swapped GEGLU half would pass them and only surface as a
wrong golden CID at deployment. These tests close that hole with what the
environment ships (torch + transformers; no diffusers/network needed):

  - random-init transformers `CLIPTextModel` / `CLIPTextModelWithProjection`
    built from a small config → state_dict → `convert_sd15_text` (+
    `convert_kandinsky2_text_projection`) → flax forward ≡ torch forward
    (the sd15 AND kandinsky text towers — reference capability:
    cog containers wrap exactly these towers).
  - hand-built torch replicas of the diffusers GEGLU fusion, attention
    projection layout, and ResnetBlock2D semantics → the corresponding
    low-level transforms (`_linear`, `_conv`, `_geglu_*`) → flax blocks.

Everything runs float32 on CPU; tolerances are a few ULP-decades above
f32 accumulation noise — a transposed weight blows them up by orders of
magnitude.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import pytest
import torch

from arbius_tpu.models.common import GEGLU, Attention, ResnetBlock
from arbius_tpu.models.sd15.convert import (
    _conv,
    _geglu_gate,
    _geglu_gate_b,
    _geglu_val,
    _geglu_val_b,
    _linear,
    convert_sd15_text,
)
from arbius_tpu.models.sd15.text_encoder import TextEncoder, TextEncoderConfig

pytestmark = [pytest.mark.slow, pytest.mark.model]

ATOL = RTOL = 2e-4  # f32 accumulation noise ceiling; transposes give O(1)


def _clip_config(act: str):
    from transformers import CLIPTextConfig

    # eos_token_id must NOT be 2: transformers keeps a legacy pooling
    # branch for eos==2 (pools at input_ids.argmax(), pre-4.24 bug
    # compatibility) — real CLIP towers ship eos=49407 (the max id)
    return CLIPTextConfig(
        vocab_size=96, hidden_size=32, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4,
        max_position_embeddings=16, hidden_act=act,
        projection_dim=24, eos_token_id=95, bos_token_id=1)


def _flax_text_config(act: str) -> TextEncoderConfig:
    return TextEncoderConfig(vocab_size=96, max_length=16, width=32,
                             layers=2, heads=4, act=act, dtype="float32")


def _ids(batch: int = 2) -> np.ndarray:
    """Token ids shaped like real prompts: BOS, tokens, first EOS, pad."""
    rng = np.random.default_rng(0)
    ids = rng.integers(3, 95, (batch, 16))
    ids[:, 0] = 1
    ids[0, 10:] = 95  # row 0: EOS at 10
    ids[1, 5:] = 95   # row 1: EOS at 5
    return ids.astype(np.int64)


def _converted_text_params(tm, act: str):
    sd = {k: v.detach().numpy() for k, v in tm.state_dict().items()}
    cfg = _flax_text_config(act)
    enc = TextEncoder(cfg)
    tmpl = jax.eval_shape(
        lambda: enc.init(jax.random.PRNGKey(0),
                         jnp.zeros((1, 16), jnp.int32)))["params"]
    params = convert_sd15_text(sd, tmpl, heads=cfg.heads,
                               head_dim=cfg.width // cfg.heads)
    return enc, params


@pytest.mark.parametrize("act", ["quick_gelu", "gelu"])
def test_text_tower_matches_torch_clip(act):
    """convert_sd15_text: flax last_hidden_state ≡ torch CLIPTextModel.

    quick_gelu is the SD-1.5 ViT-L tower; gelu is the open_clip-style
    tower the kandinsky/video text encoders use."""
    from transformers import CLIPTextModel

    torch.manual_seed(0)
    tm = CLIPTextModel(_clip_config(act)).eval()
    enc, params = _converted_text_params(tm, act)
    ids = _ids()
    with torch.no_grad():
        theirs = tm(input_ids=torch.from_numpy(ids)).last_hidden_state.numpy()
    ours = np.asarray(enc.apply({"params": params}, jnp.asarray(ids)))
    np.testing.assert_allclose(ours, theirs, atol=ATOL, rtol=RTOL)


def test_kandinsky_text_projection_matches_torch():
    """The kandinsky tower pair: CLIPTextModelWithProjection state dict →
    convert_sd15_text + convert_kandinsky2_text_projection; the flax
    EOT-pooled projected embedding ≡ torch `text_embeds` (the prior's
    conditioning input — models/kandinsky2/pipeline.py first_eos path)."""
    from transformers import CLIPTextModelWithProjection

    from arbius_tpu.models.kandinsky2.convert import (
        convert_kandinsky2_text_projection as convert_proj,
    )
    from arbius_tpu.models.kandinsky2.pipeline import TextProjection

    torch.manual_seed(1)
    tm = CLIPTextModelWithProjection(_clip_config("gelu")).eval()
    enc, params = _converted_text_params(tm, "gelu")
    sd = {k: v.detach().numpy() for k, v in tm.state_dict().items()}
    proj_mod = TextProjection(24)
    proj_tmpl = jax.eval_shape(
        lambda: proj_mod.init(jax.random.PRNGKey(0),
                              jnp.zeros((1, 32))))["params"]
    proj_params = convert_proj(sd, proj_tmpl)

    ids = _ids()
    with torch.no_grad():
        out = tm(input_ids=torch.from_numpy(ids))
    states = np.asarray(enc.apply({"params": params}, jnp.asarray(ids)))
    first_eos = np.argmax(ids == 95, axis=1)
    pooled = states[np.arange(ids.shape[0]), first_eos]
    ours = np.asarray(proj_mod.apply({"params": proj_params},
                                     jnp.asarray(pooled)))
    np.testing.assert_allclose(ours, out.text_embeds.numpy(),
                               atol=ATOL, rtol=RTOL)
    np.testing.assert_allclose(states, out.last_hidden_state.numpy(),
                               atol=ATOL, rtol=RTOL)


def test_geglu_split_matches_diffusers_fusion():
    """diffusers fuses GEGLU as one [2·inner, dim] projection chunked into
    (value, gate); the converter splits it into ff_val/ff_gate. The flax
    GEGLU over the split halves must equal `val * gelu_exact(gate)` over
    the fused torch projection."""
    torch.manual_seed(2)
    dim, inner = 12, 48
    proj = torch.nn.Linear(dim, 2 * inner)
    x = torch.randn(3, 5, dim)
    with torch.no_grad():
        val, gate = proj(x).chunk(2, dim=-1)
        theirs = (val * torch.nn.functional.gelu(gate)).numpy()

    w = proj.weight.detach().numpy()
    b = proj.bias.detach().numpy()
    params = {
        "ff_val": {"kernel": _geglu_val(w), "bias": _geglu_val_b(b)},
        "ff_gate": {"kernel": _geglu_gate(w), "bias": _geglu_gate_b(b)},
    }
    ours = np.asarray(GEGLU(inner, jnp.float32).apply(
        {"params": params}, jnp.asarray(x.numpy())))
    np.testing.assert_allclose(ours, theirs, atol=ATOL, rtol=RTOL)


def test_attention_matches_torch_sdpa():
    """The diffusers Attention projection layout (to_q/k/v bias-free,
    to_out.0 with bias) through `_linear` ≡ torch scaled_dot_product
    attention with the same projections."""
    torch.manual_seed(3)
    dim, heads, head_dim, S, Sk = 16, 4, 4, 6, 9
    to_q = torch.nn.Linear(dim, dim, bias=False)
    to_k = torch.nn.Linear(dim, dim, bias=False)
    to_v = torch.nn.Linear(dim, dim, bias=False)
    to_out = torch.nn.Linear(dim, dim)
    x = torch.randn(2, S, dim)
    ctx = torch.randn(2, Sk, dim)
    with torch.no_grad():
        def split(t):
            b, s, _ = t.shape
            return t.reshape(b, s, heads, head_dim).transpose(1, 2)

        q, k, v = split(to_q(x)), split(to_k(ctx)), split(to_v(ctx))
        o = torch.nn.functional.scaled_dot_product_attention(q, k, v)
        o = o.transpose(1, 2).reshape(2, S, dim)
        theirs = to_out(o).numpy()

    params = {
        "to_q": {"kernel": _linear(to_q.weight.detach().numpy())},
        "to_k": {"kernel": _linear(to_k.weight.detach().numpy())},
        "to_v": {"kernel": _linear(to_v.weight.detach().numpy())},
        "to_out": {"kernel": _linear(to_out.weight.detach().numpy()),
                   "bias": to_out.bias.detach().numpy()},
    }
    ours = np.asarray(Attention(heads, head_dim, jnp.float32).apply(
        {"params": params}, jnp.asarray(x.numpy()),
        context=jnp.asarray(ctx.numpy())))
    np.testing.assert_allclose(ours, theirs, atol=ATOL, rtol=RTOL)


class _TorchResnet(torch.nn.Module):
    """diffusers ResnetBlock2D semantics (default config): norm1→silu→
    conv1→(+time_emb)→norm2→silu→conv2, 1×1 conv shortcut on channel
    change."""

    def __init__(self, cin: int, cout: int, temb_dim: int):
        super().__init__()
        # GroupNorm32 uses gcd(C, 32) groups; mirror that per-norm
        self.norm1 = torch.nn.GroupNorm(int(np.gcd(cin, 32)), cin, eps=1e-5)
        self.conv1 = torch.nn.Conv2d(cin, cout, 3, padding=1)
        self.time_emb_proj = torch.nn.Linear(temb_dim, cout)
        self.norm2 = torch.nn.GroupNorm(int(np.gcd(cout, 32)), cout, eps=1e-5)
        self.conv2 = torch.nn.Conv2d(cout, cout, 3, padding=1)
        self.conv_shortcut = (torch.nn.Conv2d(cin, cout, 1)
                              if cin != cout else None)

    def forward(self, x, temb):
        h = torch.nn.functional.silu(self.norm1(x))
        h = self.conv1(h)
        h = h + self.time_emb_proj(torch.nn.functional.silu(temb))[:, :, None, None]
        h = torch.nn.functional.silu(self.norm2(h))
        h = self.conv2(h)
        skip = x if self.conv_shortcut is None else self.conv_shortcut(x)
        return skip + h


def test_resnet_block_matches_torch_reference():
    """_conv/_linear through the resnet leaf table ≡ the published
    ResnetBlock2D forward (channel-changing variant exercises skip_proj)."""
    torch.manual_seed(4)
    cin, cout, temb_dim = 8, 16, 20
    tm = _TorchResnet(cin, cout, temb_dim).eval()
    x = torch.randn(2, cin, 10, 10)
    temb = torch.randn(2, temb_dim)
    with torch.no_grad():
        theirs = tm(x, temb).numpy()

    g = lambda t: t.detach().numpy()
    params = {
        "GroupNorm32_0": {"GroupNorm_0": {"scale": g(tm.norm1.weight),
                                          "bias": g(tm.norm1.bias)}},
        "Conv_0": {"kernel": _conv(g(tm.conv1.weight)),
                   "bias": g(tm.conv1.bias)},
        "Dense_0": {"kernel": _linear(g(tm.time_emb_proj.weight)),
                    "bias": g(tm.time_emb_proj.bias)},
        "GroupNorm32_1": {"GroupNorm_0": {"scale": g(tm.norm2.weight),
                                          "bias": g(tm.norm2.bias)}},
        "Conv_1": {"kernel": _conv(g(tm.conv2.weight)),
                   "bias": g(tm.conv2.bias)},
        "skip_proj": {"kernel": _conv(g(tm.conv_shortcut.weight)),
                      "bias": g(tm.conv_shortcut.bias)},
    }
    x_nhwc = jnp.asarray(x.numpy().transpose(0, 2, 3, 1))
    ours = np.asarray(ResnetBlock(cout, jnp.float32).apply(
        {"params": params}, x_nhwc, jnp.asarray(temb.numpy())))
    np.testing.assert_allclose(ours.transpose(0, 3, 1, 2), theirs,
                               atol=ATOL, rtol=RTOL)


class _TorchSpatialNorm(torch.nn.Module):
    """diffusers SpatialNorm semantics (the MOVQ norm): GroupNorm(f)
    modulated by 1x1-conv scale/shift predicted from the nearest-upsampled
    quantized latent."""

    def __init__(self, f_channels: int, zq_channels: int):
        super().__init__()
        self.norm_layer = torch.nn.GroupNorm(int(np.gcd(f_channels, 32)),
                                             f_channels, eps=1e-6)
        self.conv_y = torch.nn.Conv2d(zq_channels, f_channels, 1)
        self.conv_b = torch.nn.Conv2d(zq_channels, f_channels, 1)

    def forward(self, f, zq):
        zq = torch.nn.functional.interpolate(zq, size=f.shape[-2:],
                                             mode="nearest")
        return self.norm_layer(f) * self.conv_y(zq) + self.conv_b(zq)


def test_movq_spatial_norm_matches_torch_reference():
    """The MOVQ decoder's SpatialNorm through the converter's leaf table
    (_spatial_norm_leaves transforms) ≡ the published formula."""
    from arbius_tpu.models.kandinsky2.movq import SpatialNorm

    torch.manual_seed(5)
    cf, cz = 8, 4
    tm = _TorchSpatialNorm(cf, cz).eval()
    f = torch.randn(2, cf, 8, 8)
    zq = torch.randn(2, cz, 4, 4)   # exercises the nearest upsample
    with torch.no_grad():
        theirs = tm(f, zq).numpy()

    # drive the ACTUAL converter leaf table: flax path -> (published key,
    # transform) — a same-shape swap in the table must fail this test
    from arbius_tpu.models.kandinsky2.convert import _spatial_norm_leaves

    sd = {k: v.detach().numpy() for k, v in tm.state_dict().items()}
    params = {}
    for path in ("norm/GroupNorm_0/scale", "norm/GroupNorm_0/bias",
                 "conv_y/kernel", "conv_y/bias",
                 "conv_b/kernel", "conv_b/bias"):
        key, tf = _spatial_norm_leaves(path)
        node = params
        parts = path.split("/")
        for part in parts[:-1]:
            node = node.setdefault(part, {})
        node[parts[-1]] = tf(sd[key])
    ours = np.asarray(SpatialNorm(jnp.float32).apply(
        {"params": params},
        jnp.asarray(f.numpy().transpose(0, 2, 3, 1)),
        jnp.asarray(zq.numpy().transpose(0, 2, 3, 1))))
    np.testing.assert_allclose(ours.transpose(0, 3, 1, 2), theirs,
                               atol=ATOL, rtol=RTOL)


def test_temporal_conv3d_transform_matches_torch():
    """The video converter's _tconv3d: a torch Conv3d with (3,1,1) kernel
    over [B, C, T, H, W] ≡ our frame-axis (3,) conv over [B, H, W, T, C]
    with the transformed kernel — the TemporalConvLayer hot path."""
    from arbius_tpu.models.video.convert import _tconv3d

    torch.manual_seed(6)
    ci, co, T, H, W = 4, 6, 5, 3, 3
    tc = torch.nn.Conv3d(ci, co, (3, 1, 1), padding=(1, 0, 0))
    x = torch.randn(2, ci, T, H, W)
    with torch.no_grad():
        theirs = tc(x).numpy()  # [B, co, T, H, W]

    import flax.linen as nn

    conv = nn.Conv(co, (3,), padding=[(1, 1)], dtype=jnp.float32)
    params = {"kernel": _tconv3d(tc.weight.detach().numpy()),
              "bias": tc.bias.detach().numpy()}
    # [B, C, T, H, W] -> [B, H, W, T, C] (the layout TemporalConvLayer
    # convolves in), back after
    x_f = jnp.asarray(x.numpy().transpose(0, 3, 4, 2, 1))
    ours = np.asarray(conv.apply({"params": params}, x_f))
    np.testing.assert_allclose(ours.transpose(0, 4, 3, 1, 2), theirs,
                               atol=ATOL, rtol=RTOL)
