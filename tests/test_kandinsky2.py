"""Kandinsky-2 family tests: stage shapes, end-to-end determinism, and
dp-mesh execution — the same contract surface as the SD-1.5 suite, for the
reference's boot-self-test model class (templates/kandinsky2.json).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from arbius_tpu.models.kandinsky2 import (
    Kandinsky2Config,
    Kandinsky2Pipeline,
    MOVQConfig,
    MOVQDecoder,
    PriorConfig,
    PriorTransformer,
    prior_sample,
)
from arbius_tpu.models.sd15 import ByteTokenizer

pytestmark = [pytest.mark.slow, pytest.mark.model]


def tiny_pipe(mesh=None):
    return Kandinsky2Pipeline(
        Kandinsky2Config.tiny(),
        tokenizer=ByteTokenizer(max_length=16, bos_id=257, eos_id=258),
        mesh=mesh)


def test_prior_transformer_shapes():
    cfg = PriorConfig.tiny()
    model = PriorTransformer(cfg)
    B = 2
    embed = jnp.zeros((B, cfg.clip_dim))
    tok = jnp.zeros((B, cfg.text_len, cfg.clip_dim))
    pooled = jnp.zeros((B, cfg.clip_dim))
    params = model.init(jax.random.PRNGKey(0), embed, jnp.zeros((B,)), tok,
                        pooled)["params"]
    out = model.apply({"params": params}, embed, jnp.ones((B,)), tok, pooled)
    assert out.shape == (B, cfg.clip_dim)
    assert out.dtype == jnp.float32


def test_prior_sample_deterministic():
    cfg = PriorConfig.tiny()
    model = PriorTransformer(cfg)
    B = 2
    tok = jnp.ones((B, cfg.text_len, cfg.clip_dim)) * 0.1
    pooled = jnp.ones((B, cfg.clip_dim)) * 0.2
    params = model.init(jax.random.PRNGKey(0), jnp.zeros((B, cfg.clip_dim)),
                        jnp.zeros((B,)), tok, pooled)["params"]
    keys = jax.vmap(jax.random.PRNGKey)(jnp.arange(B, dtype=jnp.uint32))
    g = jnp.asarray([4.0, 4.0])
    a = prior_sample(model, params, tok, pooled, keys, g, steps=3)
    b = prior_sample(model, params, tok, pooled, keys, g, steps=3)
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert np.isfinite(np.asarray(a)).all()
    # different key → different embedding
    keys2 = jax.vmap(jax.random.PRNGKey)(jnp.arange(7, 7 + B, dtype=jnp.uint32))
    c = prior_sample(model, params, tok, pooled, keys2, g, steps=3)
    assert not np.array_equal(np.asarray(a), np.asarray(c))


def test_movq_upsamples_8x():
    cfg = MOVQConfig.tiny()
    model = MOVQDecoder(cfg)
    z = jnp.zeros((1, 4, 4, cfg.latent_channels))
    params = model.init(jax.random.PRNGKey(0), z)["params"]
    out = model.apply({"params": params}, z)
    assert out.shape == (1, 32, 32, 3)


def test_pipeline_end_to_end_and_determinism():
    pipe = tiny_pipe()
    params = pipe.init_params(seed=0)
    kw = dict(width=64, height=64, num_inference_steps=2, scheduler="DDIM")
    a = pipe.generate(params, ["arbius test cat"], None, [1337], **kw)
    b = pipe.generate(params, ["arbius test cat"], None, [1337], **kw)
    assert a.shape == (1, 64, 64, 3) and a.dtype == np.uint8
    np.testing.assert_array_equal(a, b)
    c = pipe.generate(params, ["arbius test cat"], None, [1338], **kw)
    assert not np.array_equal(a, c)  # seed changes bytes


def test_pipeline_batch_content_invariance():
    """Within one program (fixed batch size = one determinism class), a
    sample's bytes must not depend on its batch NEIGHBORS' content — this
    is what makes the node's pad-to-canonical-batch policy sound. (Batch
    size itself is part of the program and may legitimately change bits;
    the node never varies it per bucket.)"""
    pipe = tiny_pipe()
    params = pipe.init_params(seed=0)
    kw = dict(width=64, height=64, num_inference_steps=2, scheduler="DDIM")
    a = pipe.generate(params, ["cat", "dog"], None, [42, 43], **kw)
    b = pipe.generate(params, ["cat", "wolf howling"], None, [42, 99], **kw)
    np.testing.assert_array_equal(a[0], b[0])


def test_pipeline_rejects_bad_geometry():
    pipe = tiny_pipe()
    params = pipe.init_params(seed=0)
    with pytest.raises(ValueError, match="multiples"):
        pipe.generate(params, ["x"], None, [1], width=60, height=64,
                      num_inference_steps=2)


def test_pipeline_on_dp_mesh():
    from arbius_tpu.parallel import MeshSpec, build_mesh

    mesh = build_mesh(MeshSpec(dp=2), devices=jax.devices()[:2])
    pipe = tiny_pipe(mesh=mesh)
    params = pipe.place_params(pipe.init_params(seed=0))
    out = pipe.generate(params, ["a", "b"], None, [1, 2], width=64,
                        height=64, num_inference_steps=2, scheduler="DDIM")
    out2 = pipe.generate(params, ["a", "b"], None, [1, 2], width=64,
                         height=64, num_inference_steps=2, scheduler="DDIM")
    assert out.shape == (2, 64, 64, 3)
    # The dp program is its own determinism class (mesh layout is part of
    # the compiled program, and the two-stage diffusion amplifies bf16
    # partitioning differences) — the mining contract is that it is
    # bit-stable with itself; miners pin their mesh layout fleet-wide.
    np.testing.assert_array_equal(out, out2)


def test_config_consistency_checks():
    from arbius_tpu.models.sd15.text_encoder import TextEncoderConfig

    # the text projection decouples text width from clip_dim; the one hard
    # invariant left is that the prior's text window fits the tokenizer
    cfg = Kandinsky2Config(
        prior=PriorConfig(clip_dim=16, width=32, layers=1, heads=2,
                          text_len=77),
        text=TextEncoderConfig.tiny())  # max_length 16 < text_len 77
    with pytest.raises(ValueError, match="max_length"):
        Kandinsky2Pipeline(cfg)
