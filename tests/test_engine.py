"""Protocol state-machine tests — the fake-chain mirror of the reference's
`contract/test/base.test.ts` + `reward.test.ts` matrices (SURVEY.md §4):
emission goldens, validator lifecycle, commit-reveal, claim fee splits,
and contestations across voter counts, outcomes, pagination, and the
slashing threshold.
"""
from __future__ import annotations

import pytest

from arbius_tpu.chain import (
    Engine,
    EngineError,
    TokenLedger,
    WAD,
    diff_mul,
    reward,
    target_ts,
)

DEPLOYER = "0x" + "d0" * 20
USER = "0x" + "01" * 20
V1 = "0x" + "11" * 20
V2 = "0x" + "12" * 20
V3 = "0x" + "13" * 20
V4 = "0x" + "14" * 20
MODEL_ADDR = "0x" + "33" * 20
TEMPLATE = b'{"meta":{"title":"test model"}}'


def make_engine(*, seed_engine=600_000 * WAD, validators=(), stake=100 * WAD):
    """Fresh engine + funded accounts; optionally pre-staked validators.

    `seed_engine` is the engine's token balance: pseudo-total-supply is
    600k minus this (EngineV1.sol:521-527), so the deployment default
    600k means supply 0 (nothing mined yet, no validator minimum) and
    e.g. 597k means supply 3000 (past both activation thresholds). Note
    stake deposits flow INTO the engine and lower the supply again.
    """
    tok = TokenLedger()
    eng = Engine(tok, start_time=1000)
    tok.mint(Engine.ADDRESS, seed_engine)
    for a in (DEPLOYER, USER, V1, V2, V3, V4):
        tok.mint(a, 1000 * WAD)
        tok.approve(a, Engine.ADDRESS, 10**30)
    for v in validators:
        eng.validator_deposit(v, v, stake)
    return eng, tok


def bootstrap_task(eng, *, fee=0, rate=0):
    mid = eng.register_model(DEPLOYER, MODEL_ADDR, fee, TEMPLATE)
    if rate:
        eng.set_solution_mineable_rate(mid, rate)
    tid = eng.submit_task(USER, 0, USER, mid, fee, b'{"prompt":"cat"}')
    return mid, tid


def solve(eng, tid, validator=V1, cid=b"\x12\x20" + b"\xaa" * 32):
    com = eng.generate_commitment(validator, tid, cid)
    eng.signal_commitment(validator, com)
    eng.mine_block()
    eng.submit_solution(validator, tid, cid)
    return cid


# -- emission goldens (reward.test.ts:154-179) -----------------------------

TARGET_TS_GOLDEN = [
    (0, 0),
    (15768000, 175735931288071485118987),
    (31536000, 300000 * WAD),
    (63072000, 450000 * WAD),
    (94608000, 525000 * WAD),
    (126144000, 562500 * WAD),
    (157680000, 581250 * WAD),
    (315360000, 599414062500000000000000),
    (3153600000, 600000 * WAD),
    (31536000000, 600000 * WAD),
]


@pytest.mark.parametrize("t,expected", TARGET_TS_GOLDEN)
def test_target_ts_golden(t, expected):
    assert target_ts(t) == expected


DIFF_MUL_GOLDEN = [
    (100000, 100 * WAD),
    (250000, 100 * WAD),
    (300000, 1 * WAD),
    (305000, 314980262473718305),
    (350000, 9612434767874),
    (355000, 3027727226196),
    (360000, 0),
    (400000, 0),
    (500000, 0),
    (600000, 0),
]


@pytest.mark.parametrize("ts,expected", DIFF_MUL_GOLDEN)
def test_diff_mul_golden(ts, expected):
    assert diff_mul(31536000, ts * WAD) == expected


def test_reward_zero_supply_default():
    assert reward(1, 0) == WAD


# -- tasks + solutions -----------------------------------------------------

def test_task_ids_chain_through_prevhash():
    eng, _ = make_engine()
    mid = eng.register_model(DEPLOYER, MODEL_ADDR, 0, TEMPLATE)
    t1 = eng.submit_task(USER, 0, USER, mid, 0, b"a")
    t2 = eng.submit_task(USER, 0, USER, mid, 0, b"a")
    assert t1 != t2  # same inputs, different id: anti-pregeneration chain
    assert eng.prevhash == t2


def test_submit_task_requires_model_and_fee():
    eng, _ = make_engine()
    with pytest.raises(EngineError, match="model does not exist"):
        eng.submit_task(USER, 0, USER, b"\x99" * 32, 0, b"x")
    mid = eng.register_model(DEPLOYER, MODEL_ADDR, 5 * WAD, TEMPLATE)
    with pytest.raises(EngineError, match="lower fee"):
        eng.submit_task(USER, 0, USER, mid, 4 * WAD, b"x")


def test_commit_reveal_happy_path_and_first_wins():
    eng, _ = make_engine(validators=(V1, V2))
    _, tid = bootstrap_task(eng)
    solve(eng, tid, V1)
    assert eng.solutions[tid].validator == V1
    # second reveal loses
    cid2 = b"\x12\x20" + b"\xbb" * 32
    com2 = eng.generate_commitment(V2, tid, cid2)
    eng.signal_commitment(V2, com2)
    eng.mine_block()
    with pytest.raises(EngineError, match="solution already submitted"):
        eng.submit_solution(V2, tid, cid2)


def test_commitment_must_age_one_block():
    eng, _ = make_engine(validators=(V1,))
    _, tid = bootstrap_task(eng)
    cid = b"\x12\x20" + b"\xaa" * 32
    eng.signal_commitment(V1, eng.generate_commitment(V1, tid, cid))
    with pytest.raises(EngineError, match="commitment must be in past"):
        eng.submit_solution(V1, tid, cid)  # same block
    with pytest.raises(EngineError, match="non existent commitment"):
        eng.submit_solution(V1, tid, b"\x12\x20" + b"\xcc" * 32)


def test_commitment_cannot_be_reset():
    eng, _ = make_engine(validators=(V1,))
    _, tid = bootstrap_task(eng)
    com = eng.generate_commitment(V1, tid, b"\x01")
    eng.signal_commitment(V1, com)
    with pytest.raises(EngineError, match="commitment exists"):
        eng.signal_commitment(V2, com)


def test_claim_fee_split():
    """fee 10: model fee 1 → model addr; 10% of the rest (0.9) accrues to
    treasury; solver gets 8.1 (EngineV1.sol:819-862)."""
    eng, tok = make_engine()
    eng.validator_deposit(V1, V1, 100 * WAD)
    mid = eng.register_model(DEPLOYER, MODEL_ADDR, 1 * WAD, TEMPLATE)
    tid = eng.submit_task(USER, 0, USER, mid, 10 * WAD, b"in")
    solve(eng, tid, V1)
    bal0 = tok.balance_of(V1)
    eng.advance_time(2001)
    eng.claim_solution(USER, tid)  # anyone can claim; reward goes to solver
    assert tok.balance_of(MODEL_ADDR) == 1 * WAD
    assert eng.accrued_fees == 9 * WAD // 10
    assert tok.balance_of(V1) - bal0 == 81 * WAD // 10
    with pytest.raises(EngineError, match="already claimed"):
        eng.claim_solution(USER, tid)


def test_claim_requires_delay():
    eng, _ = make_engine(validators=(V1,))
    _, tid = bootstrap_task(eng)
    solve(eng, tid)
    with pytest.raises(EngineError, match="not enough delay"):
        eng.claim_solution(V1, tid)


def test_claim_with_mineable_reward():
    """rate 0.1 model on an engine holding 590k: supply=10k, reward flows
    90/10 solver/treasury (reward.test.ts:189-233 flow)."""
    eng, tok = make_engine(seed_engine=590_000 * WAD)
    eng.validator_deposit(V1, V1, 100 * WAD)
    mid, tid = None, None
    mid = eng.register_model(DEPLOYER, MODEL_ADDR, 0, TEMPLATE)
    eng.set_solution_mineable_rate(mid, WAD // 10)
    # a year in: target supply 300k >> actual 10k, so diffMul caps at 100x
    eng.advance_time(31536000)
    tid = eng.submit_task(USER, 0, USER, mid, 0, b"in")
    solve(eng, tid, V1)
    bal0, tre0 = tok.balance_of(V1), tok.balance_of(eng.treasury)
    eng.advance_time(2001)
    total = (eng.get_reward() * (WAD // 10)) // WAD
    eng.claim_solution(USER, tid)
    treasury_cut = total - (total * (WAD - WAD // 10)) // WAD
    assert tok.balance_of(V1) - bal0 == total - treasury_cut
    assert tok.balance_of(eng.treasury) - tre0 == treasury_cut
    assert total > 0


def test_retract_task():
    eng, tok = make_engine()
    mid = eng.register_model(DEPLOYER, MODEL_ADDR, 0, TEMPLATE)
    tid = eng.submit_task(USER, 0, USER, mid, 10 * WAD, b"in")
    with pytest.raises(EngineError, match="did not wait long enough"):
        eng.retract_task(USER, tid)
    eng.advance_time(10001)
    bal0 = tok.balance_of(USER)
    eng.retract_task(USER, tid)
    assert tok.balance_of(USER) - bal0 == 9 * WAD
    assert eng.accrued_fees == 1 * WAD
    assert tid not in eng.tasks


def test_retract_blocked_after_solution():
    eng, _ = make_engine(validators=(V1,))
    mid = eng.register_model(DEPLOYER, MODEL_ADDR, 0, TEMPLATE)
    tid = eng.submit_task(USER, 0, USER, mid, 0, b"in")
    solve(eng, tid)
    eng.advance_time(10001)
    with pytest.raises(EngineError, match="has solution"):
        eng.retract_task(USER, tid)


# -- validator lifecycle ---------------------------------------------------

def test_validator_minimum_gates_below_supply_threshold():
    """Below 1000 supply the minimum is 0 — anyone can solve; above it,
    0.08% of supply is required (EngineV1.sol:398-404)."""
    eng, _ = make_engine(seed_engine=590_000 * WAD)  # supply = 10_000
    assert eng.get_validator_minimum() == 10_000 * WAD * 8 // 10000
    _, tid = bootstrap_task(eng)
    cid = b"\x12\x20" + b"\xaa" * 32
    eng.signal_commitment(V1, eng.generate_commitment(V1, tid, cid))
    eng.mine_block()
    with pytest.raises(EngineError, match="min staked too low"):
        eng.submit_solution(V1, tid, cid)
    eng.validator_deposit(V1, V1, 8 * WAD)  # exactly the minimum
    eng.submit_solution(V1, tid, cid)


def test_withdraw_two_step():
    eng, tok = make_engine(validators=(V1,))
    count = eng.initiate_validator_withdraw(V1, 40 * WAD)
    with pytest.raises(EngineError, match="wait longer"):
        eng.validator_withdraw(V1, count, V1)
    eng.advance_time(86400)
    bal0 = tok.balance_of(V1)
    eng.validator_withdraw(V1, count, V1)
    assert tok.balance_of(V1) - bal0 == 40 * WAD
    assert eng.validators[V1].staked == 60 * WAD


def test_withdraw_pending_counts_against_usable_stake():
    eng, _ = make_engine(seed_engine=590_000 * WAD)
    minimum = eng.get_validator_minimum()
    eng.validator_deposit(V1, V1, minimum)
    eng.initiate_validator_withdraw(V1, minimum)
    _, tid = bootstrap_task(eng)
    cid = b"\x12\x20" + b"\xaa" * 32
    eng.signal_commitment(V1, eng.generate_commitment(V1, tid, cid))
    eng.mine_block()
    with pytest.raises(EngineError, match="min staked too low"):
        eng.submit_solution(V1, tid, cid)


def test_withdraw_cancel():
    eng, _ = make_engine(validators=(V1,))
    count = eng.initiate_validator_withdraw(V1, 40 * WAD)
    eng.cancel_validator_withdraw(V1, count)
    assert eng.withdraw_pending[V1] == 0
    with pytest.raises(EngineError, match="request not exist"):
        eng.validator_withdraw(V1, count, V1)


# -- contestations ---------------------------------------------------------

def contest_setup(n_extra_voters=0, *, seed_engine=597_000 * WAD):
    """Engine above the slashing threshold even after validator deposits
    push its balance back up (supply ≥ 2000 ⇒ slash > 0)."""
    eng, tok = make_engine(seed_engine=seed_engine,
                           validators=(V1, V2, V3, V4)[:2 + n_extra_voters])
    _, tid = bootstrap_task(eng)
    solve(eng, tid, V1)
    return eng, tok, tid


def test_contestation_auto_votes_and_escrow():
    eng, _, tid = contest_setup()
    slash = eng.get_slash_amount()
    assert slash > 0
    s1, s2 = eng.validators[V1].staked, eng.validators[V2].staked
    eng.submit_contestation(V2, tid)
    # contester auto-yea, accused auto-nay, both escrowed
    assert eng.contestation_yeas[tid] == [V2]
    assert eng.contestation_nays[tid] == [V1]
    assert eng.validators[V2].staked == s2 - slash
    assert eng.validators[V1].staked == s1 - slash


def test_contestation_too_late():
    eng, _, tid = contest_setup()
    eng.advance_time(2000)
    with pytest.raises(EngineError, match="too late"):
        eng.submit_contestation(V2, tid)


def test_contestation_tie_sides_with_nays():
    """1 yea vs 1 nay ⇒ solution stands; both refunded, accused gets the
    yea escrow (single-nay branch, EngineV1.sol:1077-1095)."""
    eng, tok, tid = contest_setup()
    slash = eng.get_slash_amount()
    eng.submit_contestation(V2, tid)
    eng.advance_time(4000)
    v1_staked = eng.validators[V1].staked
    v1_bal = tok.balance_of(V1)
    eng.contestation_vote_finish(USER, tid, 10)
    assert eng.validators[V1].staked == v1_staked + slash   # refund
    assert tok.balance_of(V1) - v1_bal == slash             # yea escrow won
    # claim path ran inside finish — solution marked claimed is NOT set by
    # finish (claimed flag only set by claimSolution), but fees flowed:
    assert tid in eng.solutions


def test_contestation_success_refunds_task_fee():
    """2 yeas vs 1 nay ⇒ contestation wins: task fee back to owner, yeas
    split the nay's escrow (originator half)."""
    eng, tok = make_engine(seed_engine=597_000 * WAD,
                           validators=(V1, V2, V3))
    mid = eng.register_model(DEPLOYER, MODEL_ADDR, 0, TEMPLATE)
    tid = eng.submit_task(USER, 0, USER, mid, 5 * WAD, b"in")
    solve(eng, tid, V1)
    slash = eng.get_slash_amount()
    eng.submit_contestation(V2, tid)
    eng.vote_on_contestation(V3, tid, True)
    eng.advance_time(4000)
    user0 = tok.balance_of(USER)
    v2_0, v3_0 = tok.balance_of(V2), tok.balance_of(V3)
    v2_s, v3_s = eng.validators[V2].staked, eng.validators[V3].staked
    eng.contestation_vote_finish(USER, tid, 10)
    assert tok.balance_of(USER) - user0 == 5 * WAD          # fee refund
    total = slash  # one nay escrowed
    to_originator = total - total // 2
    assert tok.balance_of(V2) - v2_0 == to_originator
    assert tok.balance_of(V3) - v3_0 == total - to_originator
    assert eng.validators[V2].staked == v2_s + slash
    assert eng.validators[V3].staked == v3_s + slash


def test_contestation_failure_pays_solver():
    """1 yea vs 2 nays ⇒ solution stands; solver paid via the claim path
    inside finish; nays split the yea escrow (accused half)."""
    eng, tok = make_engine(seed_engine=597_000 * WAD,
                           validators=(V1, V2, V3))
    mid = eng.register_model(DEPLOYER, MODEL_ADDR, 0, TEMPLATE)
    tid = eng.submit_task(USER, 0, USER, mid, 10 * WAD, b"in")
    solve(eng, tid, V1)
    slash = eng.get_slash_amount()
    eng.submit_contestation(V2, tid)
    eng.vote_on_contestation(V3, tid, False)
    eng.advance_time(4000)
    v1_0, v3_0 = tok.balance_of(V1), tok.balance_of(V3)
    eng.contestation_vote_finish(USER, tid, 10)
    total = slash  # one yea escrowed
    to_accused = total // 2
    # V1 (nay index 0) gets accused split + solver fee share (9 of 10)
    assert tok.balance_of(V1) - v1_0 == to_accused + 9 * WAD
    assert tok.balance_of(V3) - v3_0 == total - to_accused
    assert eng.accrued_fees == 1 * WAD


def test_contestation_paginated_finish():
    eng, tok = make_engine(seed_engine=597_000 * WAD,
                           validators=(V1, V2, V3, V4))
    _, tid = bootstrap_task(eng)
    solve(eng, tid, V1)
    eng.submit_contestation(V2, tid)
    eng.vote_on_contestation(V3, tid, True)
    eng.vote_on_contestation(V4, tid, True)
    eng.advance_time(4000)
    eng.contestation_vote_finish(USER, tid, 1)   # originator only
    assert eng.contestations[tid].finish_start_index == 1
    eng.contestation_vote_finish(USER, tid, 2)   # the rest
    assert eng.contestations[tid].finish_start_index == 3
    slash = eng.contestations[tid].slash_amount
    assert eng.validators[V3].staked == 100 * WAD  # escrow refunded


def test_contestation_below_slash_threshold_is_zero_stakes():
    """Below 2000 supply getSlashAmount()=0: contestations escrow nothing
    (base.test.ts pre-threshold matrix)."""
    eng, _, tid = contest_setup(seed_engine=599_500 * WAD)  # supply 500
    assert eng.get_slash_amount() == 0
    s1 = eng.validators[V1].staked
    eng.submit_contestation(V2, tid)
    assert eng.validators[V1].staked == s1


def test_stake_age_gate_blocks_new_validators():
    """A validator staked after the contestation started cannot vote
    (vote-buying defense, EngineV1.sol:976-981)."""
    eng, _, tid = contest_setup(1)
    eng.submit_contestation(V2, tid)
    eng.advance_time(500)
    eng.validator_deposit(V4, V4, 100 * WAD)  # staked AFTER contestation
    assert eng.validator_can_vote(V4, tid) == 0x06
    with pytest.raises(EngineError, match="not allowed"):
        eng.vote_on_contestation(V4, tid, True)
    # V3 staked before: allowed
    assert eng.validator_can_vote(V3, tid) == 0


def test_validator_can_vote_codes():
    eng, _, tid = contest_setup(1)
    assert eng.validator_can_vote(V3, b"\x00" * 32) == 0x01  # no contestation
    eng.submit_contestation(V2, tid)
    assert eng.validator_can_vote(V2, tid) == 0x03           # already voted
    assert eng.validator_can_vote(USER, tid) == 0x04         # never staked
    eng.vote_on_contestation(V3, tid, True)
    eng.advance_time(4001)
    assert eng.validator_can_vote(V3, tid) == 0x02           # period over


def test_claim_blocked_by_contestation():
    eng, _, tid = contest_setup()
    eng.submit_contestation(V2, tid)
    eng.advance_time(2001)
    with pytest.raises(EngineError, match="has contestation"):
        eng.claim_solution(USER, tid)


# -- pause gates -----------------------------------------------------------

def test_pause_gates_entry_points():
    eng, _ = make_engine(validators=(V1,))
    _, tid = bootstrap_task(eng)
    eng.set_paused(True)
    for call in [
        lambda: eng.submit_task(USER, 0, USER, b"\x01" * 32, 0, b"x"),
        lambda: eng.signal_commitment(V1, b"\x02" * 32),
        lambda: eng.submit_solution(V1, tid, b"\x03"),
        lambda: eng.register_model(DEPLOYER, MODEL_ADDR, 0, b"t"),
        lambda: eng.validator_deposit(V1, V1, WAD),
        lambda: eng.claim_solution(USER, tid),
        lambda: eng.submit_contestation(V1, tid),
        lambda: eng.retract_task(USER, tid),
    ]:
        with pytest.raises(EngineError, match="paused"):
            call()
    eng.set_paused(False)
    solve(eng, tid)  # works again


# -- events ----------------------------------------------------------------

def test_events_stream_to_subscribers():
    eng, _ = make_engine(validators=(V1,))
    seen = []
    eng.subscribe(lambda ev: seen.append(ev.name))
    _, tid = bootstrap_task(eng)
    solve(eng, tid)
    assert "TaskSubmitted" in seen
    assert "SignalCommitment" in seen
    assert "SolutionSubmitted" in seen
