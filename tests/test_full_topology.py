"""Full-published-topology execution proofs — env-gated.

The regular suite runs tiny configs (CI hosts); these tests run each
family's FULL published topology end-to-end at small spatial/step counts
(params are shape-independent, so this exercises every real channel
width, head split, and converter-facing module on real trees: SD-1.5
860M, Kandinsky-2 ~3.0B across prior/decoder/MOVQ/text, ModelScope-class
UNet3D ~1.9B, RVM 3.8M). On a 1-core CPU host each diffusion family
takes ~15-25 min to compile+run, so they are opt-in:

    ARBIUS_FULL_TOPOLOGY=1 JAX_PLATFORMS=cpu python -m pytest \
        tests/test_full_topology.py -q

All four were executed green on 2026-07-30 (this round's working host).
"""
from __future__ import annotations

import os

import numpy as np
import pytest

pytestmark = [
    pytest.mark.slow, pytest.mark.model,
    pytest.mark.skipif(not os.environ.get("ARBIUS_FULL_TOPOLOGY"),
                       reason="set ARBIUS_FULL_TOPOLOGY=1 (each family "
                              "compiles ~15-25 min on a 1-core host)"),
]


def _tok():
    from arbius_tpu.models.sd15 import ByteTokenizer

    return ByteTokenizer()


def test_sd15_full_topology_generates():
    from arbius_tpu.models.sd15 import SD15Config, SD15Pipeline

    pipe = SD15Pipeline(SD15Config(), tokenizer=_tok())
    params = pipe.init_params(seed=0, height=128, width=128)
    img = pipe.generate(params, ["arbius test cat"], [""], [1337],
                        width=128, height=128, num_inference_steps=2,
                        scheduler="DDIM")
    assert img.shape == (1, 128, 128, 3) and img.dtype == np.uint8


def test_kandinsky2_full_topology_generates():
    from arbius_tpu.models.kandinsky2 import Kandinsky2Config, Kandinsky2Pipeline

    pipe = Kandinsky2Pipeline(Kandinsky2Config(), tokenizer=_tok())
    params = pipe.init_params(seed=0, height=128, width=128)
    img = pipe.generate(params, ["arbius test cat"], [""], [1337],
                        width=128, height=128, num_inference_steps=2)
    assert img.shape == (1, 128, 128, 3) and img.dtype == np.uint8


def test_video_full_topology_generates():
    from arbius_tpu.models.video import Text2VideoConfig, Text2VideoPipeline

    pipe = Text2VideoPipeline(Text2VideoConfig(), tokenizer=_tok())
    params = pipe.init_params(seed=0)
    v = pipe.generate(params, ["arbius test cat"], [""], [1337],
                      num_frames=2, width=128, height=128,
                      num_inference_steps=2, scheduler="DDIM")
    assert v.shape == (1, 2, 128, 128, 3) and v.dtype == np.uint8


def test_rvm_full_topology_mattes():
    from arbius_tpu.models.rvm import RVMPipeline, RVMPipelineConfig

    pipe = RVMPipeline(RVMPipelineConfig())
    params = pipe.init_params(seed=0, height=64, width=64)
    rng = np.random.default_rng(0)
    video = rng.integers(0, 255, (2, 64, 64, 3), dtype=np.uint8)
    out = pipe.matte(params, video, output_type="green-screen")
    assert out.shape == video.shape and out.dtype == np.uint8
