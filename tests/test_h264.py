"""H.264 I_PCM encoder round-trip tests.

The environment ships no third-party H.264 decoder (and the determinism
contract forbids depending on one), so validation is a from-scratch
decoder (codecs/h264_decode.py) driven over the encoder's own output:
I_PCM is lossless by specification, so the decode must recover the
encoder's YCbCr samples BIT-EXACTLY, through the full mp4→avcC→NAL→
slice→macroblock path.
"""
from __future__ import annotations

import numpy as np
import pytest

from arbius_tpu.codecs import encode_mp4_h264
from arbius_tpu.codecs.h264 import (
    BitWriter,
    encode_h264,
    escape_rbsp,
    rgb_to_yuv420,
    sps_bytes,
)
from arbius_tpu.codecs.h264_decode import (
    BitReader,
    decode_h264_mp4_yuv,
    parse_sps,
    unescape_rbsp,
    yuv420_to_rgb,
)


def _frames(t, h, w, seed=0):
    rng = np.random.RandomState(seed)
    return rng.randint(0, 256, (t, h, w, 3), np.uint8)


# -- bitstream primitives -------------------------------------------------

@pytest.mark.parametrize("values", [[0, 1, 2, 25, 255, 100000]])
def test_exp_golomb_roundtrip(values):
    w = BitWriter()
    for v in values:
        w.ue(v)
    for v in [-5, 0, 3, -100, 7]:
        w.se(v)
    w.trailing()
    r = BitReader(w.bytes())
    assert [r.ue() for _ in values] == values
    assert [r.se() for _ in range(5)] == [-5, 0, 3, -100, 7]


def test_emulation_prevention_roundtrip():
    # every escape-relevant pattern, incl. chained zeros
    raw = bytes([0, 0, 0, 0, 0, 1, 0, 0, 2, 0, 0, 3, 1, 0, 0]) + b"\x00" * 8
    esc = escape_rbsp(raw)
    assert b"\x00\x00\x00" not in esc[:len(esc) - 2]
    assert unescape_rbsp(esc) == raw


def test_sps_geometry_with_cropping():
    sps = parse_sps(unescape_rbsp(sps_bytes(1000, 568)[1:]))
    assert (sps["width"], sps["height"]) == (1000, 568)
    assert sps["mbs_w"] == 63 and sps["mbs_h"] == 36
    assert sps["profile"] == 66


# -- full round trip ------------------------------------------------------

@pytest.mark.parametrize("t,h,w", [
    (2, 32, 48),     # MB-aligned
    (3, 40, 56),     # needs cropping (40=2.5 MBs, 56=3.5 MBs)
    (1, 128, 128),   # RVM probe-clip shape
])
def test_mp4_roundtrip_lossless_yuv(t, h, w):
    frames = _frames(t, h, w)
    data = encode_mp4_h264(frames, fps=8)
    decoded = decode_h264_mp4_yuv(data)
    assert len(decoded) == t
    for i in range(t):
        y, cb, cr = rgb_to_yuv420(frames[i])
        dy, dcb, dcr = decoded[i]
        np.testing.assert_array_equal(dy, y)      # I_PCM is lossless
        np.testing.assert_array_equal(dcb, cb)
        np.testing.assert_array_equal(dcr, cr)


def test_encode_deterministic():
    frames = _frames(2, 32, 32, seed=7)
    assert encode_mp4_h264(frames, fps=8) == encode_mp4_h264(frames, fps=8)


def test_pcm_zero_samples_force_emulation_prevention():
    """All-zero YCbCr payloads generate long 00 runs inside the slice;
    the escaped NAL must still round-trip bit-exactly."""
    from arbius_tpu.codecs.h264 import idr_slice_ipcm, pps_bytes
    from arbius_tpu.codecs.h264_decode import (
        decode_idr_ipcm,
        parse_pps,
    )

    y = np.zeros((16, 16), np.uint8)
    c = np.zeros((8, 8), np.uint8)
    nal = idr_slice_ipcm(y, c, c, idr_pic_id=0)
    assert b"\x00\x00\x03" in nal  # escaping actually engaged
    sps = parse_sps(unescape_rbsp(sps_bytes(16, 16)[1:]))
    pps = parse_pps(unescape_rbsp(pps_bytes()[1:]))
    dy, dcb, dcr = decode_idr_ipcm(unescape_rbsp(nal[1:]), sps, pps)
    np.testing.assert_array_equal(dy, y)
    np.testing.assert_array_equal(dcb, c)
    np.testing.assert_array_equal(dcr, c)


def test_yuv_rgb_color_transform_bounds():
    """Limited-range transform keeps Y in [16,235]-ish and survives the
    inverse within rounding error."""
    frames = _frames(1, 16, 16, seed=3)
    y, cb, cr = rgb_to_yuv420(frames[0])
    assert y.min() >= 16 and y.max() <= 235
    rgb = yuv420_to_rgb(y, cb, cr)
    # chroma subsampling + integer rounding: loose tolerance, right shape
    assert rgb.shape == frames[0].shape
    assert abs(int(rgb.astype(int).mean()) - int(frames[0].mean())) < 16


def test_browser_relevant_structure():
    """The avc1 boxes a <video> demuxer needs: ftyp brand, avcC with
    inline SPS/PPS, length-prefixed IDR samples."""
    data = encode_mp4_h264(_frames(2, 32, 32), fps=8)
    assert data[4:8] == b"ftyp"
    assert b"avc1" in data and b"avcC" in data
    assert b"jpeg" not in data[-2000:]  # no MJPEG sample entry anymore


def test_multi_sample_per_chunk_avc1_decodes_all_frames():
    """External muxers pack many samples per chunk; the avc1 demux must
    walk stsc run expansion, not zip(stco, stsz) (which truncates)."""
    import struct

    from arbius_tpu.codecs.h264 import encode_h264
    from arbius_tpu.codecs.mp4 import (
        _box,
        _full,
        _hdlr,
        _mdhd,
        _mvhd,
        _stsd,
        _tkhd,
        _visual_entry,
    )
    from arbius_tpu.codecs.h264 import avcc_box_payload

    frames = _frames(4, 32, 32, seed=9)
    sps, pps, aus = encode_h264(frames)
    samples = [struct.pack(">I", len(au)) + au for au in aus]
    ftyp = _box(b"ftyp", b"isom" + struct.pack(">I", 0x200) + b"isomiso2mp41")
    mdat = _box(b"mdat", b"".join(samples))
    data_start = len(ftyp) + 8
    chunk2 = data_start + len(samples[0]) + len(samples[1])
    stts = _full(b"stts", 0, 0, struct.pack(">III", 1, 4, 1))
    stsc = _full(b"stsc", 0, 0, struct.pack(">IIII", 1, 1, 2, 1))  # 2/chunk
    stsz = _full(b"stsz", 0, 0, struct.pack(">II", 0, 4)
                 + b"".join(struct.pack(">I", len(s)) for s in samples))
    stco = _full(b"stco", 0, 0, struct.pack(">III", 2, data_start, chunk2))
    entry = _visual_entry(b"avc1", 32, 32, b"arbius avc",
                          _box(b"avcC", avcc_box_payload(sps, pps)))
    stbl = _box(b"stbl", _stsd(entry) + stts + stsc + stsz + stco)
    dref = _full(b"dref", 0, 0,
                 struct.pack(">I", 1) + _full(b"url ", 0, 1, b""))
    minf = _box(b"minf", _full(b"vmhd", 0, 1, struct.pack(">HHHH", 0, 0, 0,
                                                          0))
                + _box(b"dinf", dref) + stbl)
    mdia = _box(b"mdia", _mdhd(4, 4) + _hdlr() + minf)
    trak = _box(b"trak", _tkhd(4, 32, 32) + mdia)
    moov = _box(b"moov", _mvhd(4, 4) + trak)
    decoded = decode_h264_mp4_yuv(ftyp + mdat + moov)
    assert len(decoded) == 4
    for i in range(4):
        y, _, _ = rgb_to_yuv420(frames[i])
        np.testing.assert_array_equal(decoded[i][0], y)


def test_slice_header_with_deblocking_enabled_parses():
    """disable_deblocking_filter_idc != 1 carries alpha/beta offsets
    (spec 7.3.3) — an external stream with deblocking ON (idc 0) must
    still parse (I_PCM samples bypass the filter)."""
    from arbius_tpu.codecs.h264 import BitWriter, escape_rbsp, pps_bytes
    from arbius_tpu.codecs.h264_decode import decode_idr_ipcm, parse_pps

    y = np.arange(256, dtype=np.uint8).reshape(16, 16)
    c = np.full((8, 8), 77, np.uint8)
    w = BitWriter()
    w.ue(0); w.ue(7); w.ue(0)      # first_mb, slice_type I, pps_id
    w.u(0, 4)                       # frame_num
    w.ue(0)                         # idr_pic_id
    w.u(0, 1); w.u(0, 1)            # dec_ref_pic_marking
    w.se(0)                         # slice_qp_delta
    w.ue(0)                         # disable_deblocking_filter_idc = 0 (ON)
    w.se(2); w.se(-2)               # alpha/beta offsets — must be consumed
    w.ue(25); w.align_zero()
    w.raw(y.tobytes()); w.raw(c.tobytes()); w.raw(c.tobytes())
    w.trailing()
    rbsp = w.bytes()
    sps = parse_sps(unescape_rbsp(sps_bytes(16, 16)[1:]))
    pps = parse_pps(unescape_rbsp(pps_bytes()[1:]))
    del escape_rbsp  # (slice parsed pre-escape here)
    dy, dcb, dcr = decode_idr_ipcm(rbsp, sps, pps)
    np.testing.assert_array_equal(dy, y)
    np.testing.assert_array_equal(dcb, c)


def test_vectorized_slice_equals_scalar_construction():
    """The numpy slice body must be byte-identical to the readable
    per-MB BitWriter construction (the round-4 goldens pin these bytes)."""
    from arbius_tpu.codecs.h264 import _nal, idr_slice_ipcm

    rng = np.random.RandomState(21)
    y = rng.randint(0, 256, (48, 32), np.uint8)
    cb = rng.randint(0, 256, (24, 16), np.uint8)
    cr = rng.randint(0, 256, (24, 16), np.uint8)

    def scalar(y, cb, cr, idr_pic_id):
        w = BitWriter()
        w.ue(0); w.ue(7); w.ue(0)
        w.u(0, 4)
        w.ue(idr_pic_id & 1)
        w.u(0, 1); w.u(0, 1)
        w.se(0)
        w.ue(1)
        for my in range(y.shape[0] // 16):
            for mx in range(y.shape[1] // 16):
                w.ue(25)
                w.align_zero()
                w.raw(y[my*16:(my+1)*16, mx*16:(mx+1)*16].tobytes())
                w.raw(cb[my*8:(my+1)*8, mx*8:(mx+1)*8].tobytes())
                w.raw(cr[my*8:(my+1)*8, mx*8:(mx+1)*8].tobytes())
        w.trailing()
        return _nal(3, 5, w.bytes())

    for pid in (0, 1):
        assert idr_slice_ipcm(y, cb, cr, pid) == scalar(y, cb, cr, pid)


def test_audio_trak_first_still_finds_video():
    """External MP4s often put an audio trak before the video trak; the
    demux must select by hdlr handler_type, not take the first trak."""
    import struct

    from arbius_tpu.codecs.mp4 import _box, _full
    from arbius_tpu.codecs.mp4_demux import decode_video_mp4

    frames = _frames(2, 32, 32, seed=4)
    good = encode_mp4_h264(frames, fps=8)
    # splice a minimal AUDIO trak (hdlr 'soun', empty stbl) before the
    # real video trak inside moov
    moov_off = good.rfind(b"moov") - 4
    moov_size = struct.unpack(">I", good[moov_off:moov_off + 4])[0]
    moov_body = good[moov_off + 8:moov_off + moov_size]
    hdlr = _full(b"hdlr", 0, 0,
                 struct.pack(">I", 0) + b"soun" + b"\x00" * 12 + b"a\x00")
    audio_trak = _box(b"trak", _box(b"mdia", hdlr + _box(
        b"minf", _box(b"stbl", b""))))
    new_moov = _box(b"moov", audio_trak + moov_body)
    data = good[:moov_off] + new_moov
    decoded = decode_video_mp4(data)
    assert decoded.shape == (2, 32, 32, 3)


def test_poc_type0_slice_header_parses():
    """poc_type-0 SPS puts pic_order_cnt_lsb in every slice header; the
    decoder must consume it (external-stream compatibility)."""
    from arbius_tpu.codecs.h264 import BitWriter
    from arbius_tpu.codecs.h264_decode import decode_idr_ipcm

    # hand-built poc_type-0 SPS dict (what parse_sps would produce)
    sps = {"profile": 66, "level": 51, "log2_max_frame_num": 4,
           "poc_type": 0, "log2_max_poc_lsb": 6,
           "mbs_w": 1, "mbs_h": 1, "width": 16, "height": 16}
    pps = {"pic_init_qp": 26, "deblock_control": 0}
    y = np.arange(256, dtype=np.uint8).reshape(16, 16)
    c = np.full((8, 8), 9, np.uint8)
    w = BitWriter()
    w.ue(0); w.ue(7); w.ue(0)
    w.u(0, 4)                       # frame_num
    w.ue(0)                         # idr_pic_id
    w.u(33, 6)                      # pic_order_cnt_lsb (log2 6)
    w.u(0, 1); w.u(0, 1)
    w.se(0)
    w.ue(25); w.align_zero()
    w.raw(y.tobytes()); w.raw(c.tobytes()); w.raw(c.tobytes())
    w.trailing()
    dy, dcb, _ = decode_idr_ipcm(w.bytes(), sps, pps)
    np.testing.assert_array_equal(dy, y)
    np.testing.assert_array_equal(dcb, c)


def test_inter_predicted_input_rejected_not_truncated():
    """ISSUE satellite: a VCL NAL the decoder can't reproduce (types 1-4,
    inter/partitioned slices) must raise, not silently skip — skipping
    matted a truncated clip from external avc1 files."""
    data = encode_mp4_h264(_frames(2, 32, 32), fps=8)
    i = data.index(b"mdat") + 4       # first sample: 4-byte len + NAL
    assert data[i + 4] & 0x1F == 5    # our encoder emits IDR slices
    bad = bytearray(data)
    bad[i + 4] = (3 << 5) | 1         # rewrite as a non-IDR slice
    with pytest.raises(ValueError, match="all-IDR"):
        decode_h264_mp4_yuv(bytes(bad))
