"""Content store + pinning: solution data availability (VERDICT #4).

Invariant under test everywhere: stored-bytes CID == cid_of_solution_files
== the CID the node commits — and those bytes are retrievable over the
node's /ipfs gateway (the reference delegates this to an external IPFS
daemon/Pinata, miner/src/ipfs.ts:28-114).
"""
from __future__ import annotations

import io
import json
import urllib.request

import pytest

from arbius_tpu.l0.base58 import b58encode
from arbius_tpu.l0.cid import cid_hex, cid_of_solution_files, dag_of_file
from arbius_tpu.node import ContentStore, HttpDaemonPinner, LocalPinner, PinMismatchError, cid_b58
from tests.test_node import build_world, drain, submit, task_input


def test_cid_b58_normalizes_all_forms(tmp_path):
    cid = dag_of_file(b"hello").cid
    b58 = b58encode(cid)
    assert cid_b58(cid) == b58
    assert cid_b58("0x" + cid.hex()) == b58
    assert cid_b58(b58) == b58
    with pytest.raises(ValueError):
        cid_b58("0x1221" + "00" * 32)  # wrong multihash prefix
    with pytest.raises(ValueError):
        cid_b58(b"\x12\x20short")


def test_store_roundtrip_and_invariant(tmp_path):
    store = ContentStore(tmp_path)
    files = {"out-1.png": b"\x89PNG fake", "out-2.txt": b"hi" * 200_000}
    root = store.put_files(files)
    assert root == cid_of_solution_files(files)
    manifest = store.get_dir(root)
    assert set(manifest) == set(files)
    for name, data in files.items():
        assert store.get_file(manifest[name]) == data
        assert store.resolve(root, name) == data
    assert store.resolve(root, "nope") is None
    assert store.has(root) and store.has("0x" + root.hex())
    # idempotent re-put
    assert store.put_files(files) == root
    assert store.stats()["dirs"] == 1


def test_store_blob(tmp_path):
    store = ContentStore(tmp_path)
    cid = store.put_blob(b'{"prompt": "x"}')
    assert cid == dag_of_file(b'{"prompt": "x"}').cid
    assert store.get_file(cid) == b'{"prompt": "x"}'
    assert store.get_file(dag_of_file(b"other").cid) is None


def test_node_stores_solution_and_task_input(tmp_path):
    eng, tok, chain, node, mid = build_world()
    node.store = ContentStore(tmp_path)
    tid = submit(eng, mid, "store me")
    drain(node)
    sol = eng.solutions[bytes.fromhex(tid[2:])]
    # committed CID is fetchable from the store with matching bytes
    manifest = node.store.get_dir(sol.cid)
    assert manifest is not None and "out-1.png" in manifest
    assert node.store.resolve(sol.cid, "out-1.png").startswith(b"\x89PNG")
    # the raw task input was mirrored (pinTaskInput made real)
    raw = eng.task_input_data[bytes.fromhex(tid[2:])]
    assert node.store.get_file(dag_of_file(raw).cid) == raw


def test_gateway_serves_solution_bytes(tmp_path):
    from arbius_tpu.node.rpc import ControlRPC

    eng, tok, chain, node, mid = build_world()
    node.store = ContentStore(tmp_path)
    tid = submit(eng, mid, "gateway")
    drain(node)
    sol = eng.solutions[bytes.fromhex(tid[2:])]
    rpc = ControlRPC(node)
    rpc.start()
    try:
        base = f"http://127.0.0.1:{rpc.port}"
        b58 = cid_b58(sol.cid)
        listing = json.loads(urllib.request.urlopen(
            f"{base}/ipfs/{b58}").read())
        assert "out-1.png" in listing["files"]
        data = urllib.request.urlopen(
            f"{base}/ipfs/{b58}/out-1.png").read()
        assert data.startswith(b"\x89PNG")
        assert cid_of_solution_files({"out-1.png": data}) == sol.cid
        # explorer links into the gateway
        html = urllib.request.urlopen(f"{base}/explorer").read().decode()
        assert f"/ipfs/{b58}" in html
    finally:
        rpc.stop()


def test_local_pinner(tmp_path):
    pinner = LocalPinner(ContentStore(tmp_path))
    files = {"a.txt": b"aaa"}
    assert pinner.pin_files(files) == cid_of_solution_files(files)


class FakeDaemonResponse(io.BytesIO):
    def __enter__(self):
        return self

    def __exit__(self, *a):
        return False


def _fake_opener(responses: list[bytes]):
    captured = []

    def opener(req, timeout=None):
        captured.append(req)
        return FakeDaemonResponse(responses.pop(0))

    return opener, captured


def test_http_daemon_pinner_verifies_root(tmp_path):
    files = {"out-1.png": b"\x89PNG bytes"}
    root58 = b58encode(cid_of_solution_files(files))
    good = json.dumps({"Name": "out-1.png", "Hash": "Qmfile"}).encode() + \
        b"\n" + json.dumps({"Name": "", "Hash": root58}).encode()
    opener, captured = _fake_opener([good])
    pinner = HttpDaemonPinner("http://fake:5001", opener=opener)
    assert pinner.pin_files(files) == cid_of_solution_files(files)
    req = captured[0]
    assert "cid-version=0" in req.full_url and "wrap-with-directory=true" \
        in req.full_url
    assert b"\x89PNG bytes" in req.data

    bad = json.dumps({"Name": "", "Hash": "QmWrongRoot"}).encode()
    opener, _ = _fake_opener([bad])
    with pytest.raises(PinMismatchError):
        HttpDaemonPinner("http://fake:5001", opener=opener).pin_files(files)
