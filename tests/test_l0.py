"""L0 deterministic-kernel tests.

Golden vectors come from the reference's own test suites so the artifact
layer is provably byte-compatible:
  - CIDs of fixture files: `contract/test/ipfs.ts:52-55` (same values
    asserted against the live daemon in `miner/test/ipfs.test.ts:106-109`).
  - keccak vectors: standard Ethereum test values.
"""
import hashlib

import pytest

from arbius_tpu.l0 import (
    abi_encode,
    b58decode,
    b58encode,
    cid_hex,
    cid_of_solution_files,
    cid_onchain,
    dag_of_directory,
    dag_of_file,
    generate_commitment_hex,
    hex_to_cid,
    cid_to_hex,
    keccak256,
    keccak256_hex,
    taskid2seed,
)
from arbius_tpu.l0.cid import CHUNK_SIZE, MAX_LINKS_PER_BLOCK, unixfs_file_leaf, cidv0
from arbius_tpu.l0.varint import decode_varint, encode_varint

GOLDEN_CIDS = {
    # contract/test/ipfs.ts:52-55
    "ipfs_a.bin": "0x1220e844b8764c00d4a76ac03930a3d8f32f3df59aea3ed0ade4c3bc38a3b23a31d9",
    "ipfs_b.bin": "0x1220f782bf27d7dfa16c5556ae0e19d41a73fc380a28455abcedecd70460505f022b",
    "ipfs_c.bin": "0x1220c32cae42b7d6ed6efd2512fd7dac6530cbd96cbcc19a3d1c336ace8e401f1c3a",
    "ipfs_d.bin": "0x1220f4ad8a3bd3189da2ad909ee41148d6893d8c629c410f7f2c7e3fae75aade79c8",
}


class TestVarint:
    @pytest.mark.parametrize("n,expected", [
        (0, b"\x00"),
        (1, b"\x01"),
        (127, b"\x7f"),
        (128, b"\x80\x01"),
        (262144, b"\x80\x80\x10"),
        (300, b"\xac\x02"),
    ])
    def test_encode(self, n, expected):
        assert encode_varint(n) == expected

    def test_roundtrip(self):
        for n in [0, 1, 127, 128, 16383, 16384, 2**32, 2**53]:
            value, off = decode_varint(encode_varint(n))
            assert value == n
            assert off == len(encode_varint(n))


class TestGoldenCIDs:
    @pytest.mark.parametrize("name", sorted(GOLDEN_CIDS))
    def test_onchain_matches_reference_vectors(self, fixtures_dir, name):
        content = (fixtures_dir / name).read_bytes()
        assert cid_hex(cid_onchain(content)) == GOLDEN_CIDS[name]

    @pytest.mark.parametrize("name", sorted(GOLDEN_CIDS))
    def test_daemon_single_block_agrees_with_onchain(self, fixtures_dir, name):
        # For non-empty content < chunk size the daemon profile and the
        # on-chain encoder must produce the identical block (submitTask
        # hashes input on-chain, the miner mirrors it to the daemon).
        content = (fixtures_dir / name).read_bytes()
        assert cid_hex(dag_of_file(content).cid) == GOLDEN_CIDS[name]


class TestMultiBlock:
    def test_chunk_boundary_single_block(self):
        content = b"\xab" * CHUNK_SIZE
        node = dag_of_file(content)
        assert node.cid == cidv0(unixfs_file_leaf(content))

    def test_multi_chunk_structure(self):
        content = bytes(range(256)) * 4096  # 1 MiB -> 4 chunks
        node = dag_of_file(content)
        assert node.content_size == len(content)
        # parent node: block itself is small, tsize exceeds content
        assert node.tsize > len(content)
        # determinism
        assert dag_of_file(content).cid == node.cid

    def test_chunking_changes_cid(self):
        a = dag_of_file(b"\x00" * (CHUNK_SIZE + 1))
        b = dag_of_file(b"\x00" * CHUNK_SIZE)
        assert a.cid != b.cid

    def test_wide_file_two_levels(self):
        # > 174 chunks forces a second parent level
        content = b"z" * (CHUNK_SIZE * (MAX_LINKS_PER_BLOCK + 1))
        node = dag_of_file(content)
        assert node.content_size == len(content)

    def test_goipfs_golden_empty_dir(self):
        # well-known go-ipfs empty-directory CID — proves dag-pb directory
        # serialization matches the daemon the reference miner pins through
        from arbius_tpu.l0 import cid_base58
        assert cid_base58(dag_of_directory({}).cid) == (
            "QmUNLLsPACCz1vLxQVkXqqLX5R1X345qqfHbsf67hvA3Nn")

    def test_goipfs_golden_empty_file(self):
        # well-known go-ipfs empty-file CID (QmbFMke1...)
        from arbius_tpu.l0 import cid_base58
        assert cid_base58(dag_of_file(b"").cid) == (
            "QmbFMke1KXqnYyBBWxB74N4c5SBnJMVAiMNRcGu6x1AwQH")

    def test_directory_wrap(self):
        files = {"out-1.png": b"\x89PNG fake", "out-2.png": b"more"}
        root = dag_of_directory(files)
        # order-insensitive: links sorted by name
        root2 = dag_of_directory(dict(reversed(list(files.items()))))
        assert root.cid == root2.cid
        assert cid_of_solution_files(files) == root.cid
        # different content -> different root
        assert dag_of_directory({"out-1.png": b"x"}).cid != root.cid


class TestDagPbStructure:
    """Decode our own multi-block parent with an independent minimal protobuf
    reader and assert the dag-pb/UnixFS wire layout (field numbers, link
    ordering, blocksizes) — guards the >256 KiB path that has no external
    golden vector."""

    @staticmethod
    def _read_fields(buf):
        fields = []
        off = 0
        while off < len(buf):
            tag, off = decode_varint(buf, off)
            fno, wt = tag >> 3, tag & 7
            if wt == 0:
                val, off = decode_varint(buf, off)
            elif wt == 2:
                ln, off = decode_varint(buf, off)
                val = buf[off:off + ln]
                off += ln
            else:
                raise AssertionError(f"unexpected wire type {wt}")
            fields.append((fno, val))
        return fields

    def test_parent_block_layout(self):
        from arbius_tpu.l0.cid import _file_parent, unixfs_file_leaf, DagNode
        c1, c2 = b"x" * CHUNK_SIZE, b"y" * 100
        leaves = []
        for ch in (c1, c2):
            blk = unixfs_file_leaf(ch)
            leaves.append(DagNode(cidv0(blk), len(blk), len(blk), len(ch)))
        parent = _file_parent(leaves)
        # rebuild the parent block to decode it
        from arbius_tpu.l0.cid import _pblink, _lenprefixed
        links = b"".join(_pblink(c, "") for c in leaves)
        unixfs = b"\x08\x02" + b"\x18" + encode_varint(CHUNK_SIZE + 100)
        unixfs += b"\x20" + encode_varint(CHUNK_SIZE) + b"\x20" + encode_varint(100)
        block = links + _lenprefixed(b"\x0a", unixfs)
        assert cidv0(block) == parent.cid

        fields = self._read_fields(block)
        # canonical dag-pb: Links (field 2) before Data (field 1)
        assert [f for f, _ in fields] == [2, 2, 1]
        for (_, link), leaf in zip(fields[:2], leaves):
            lf = self._read_fields(link)
            assert lf[0] == (1, leaf.cid)          # Hash
            assert lf[1] == (2, b"")               # empty Name IS emitted
            assert lf[2] == (3, leaf.tsize)        # Tsize
        unixfs_fields = self._read_fields(fields[2][1])
        assert unixfs_fields[0] == (1, 2)                       # Type=File
        assert unixfs_fields[1] == (3, CHUNK_SIZE + 100)        # filesize
        assert unixfs_fields[2] == (4, CHUNK_SIZE)              # blocksizes
        assert unixfs_fields[3] == (4, 100)


class TestBase58:
    def test_roundtrip(self):
        for data in [b"", b"\x00", b"\x00\x01", b"hello world", bytes(range(256))]:
            assert b58decode(b58encode(data)) == data

    def test_known_vector(self):
        # classic bitcoin-alphabet vector
        assert b58encode(b"hello world") == "StV1DL6CwTryKyV"

    def test_cid_hex_roundtrip(self):
        h = GOLDEN_CIDS["ipfs_a.bin"]
        assert cid_to_hex(hex_to_cid(h)) == h
        # Qm prefix for 0x1220 multihashes
        assert hex_to_cid(h).startswith("Qm")


class TestKeccak:
    def test_empty(self):
        assert keccak256(b"").hex() == (
            "c5d2460186f7233c927e7db2dcc703c0e500b653ca82273b7bfad8045d85a470")

    def test_abc(self):
        assert keccak256(b"abc").hex() == (
            "4e03657aea45a94fc7d47ba826c8d667c0d1e6e33a64a036ec44f58fa12d6c45")

    def test_long_input_multiple_blocks(self):
        # > rate (136 bytes) exercises multi-block absorb
        data = b"a" * 1000
        assert len(keccak256(data)) == 32
        assert keccak256(data) == keccak256(b"a" * 1000)
        assert keccak256(data) != keccak256(b"a" * 999)

    def test_single_byte_pad_boundary(self):
        # len % 136 == 135: 0x01 and 0x80 pad bits merge into one 0x81 byte.
        # Golden from the reference implementation class (eth keccak256 of
        # 135 'a' bytes).
        assert keccak256(b"a" * 135).hex() == (
            "34367dc248bbd832f4e3e69dfaac2f92638bd0bbd18f2912ba4ef454919cf446")
        # full-rate multiple boundary too
        assert len(keccak256(b"a" * 136)) == 32


class TestAbiEncode:
    def test_static_layout(self):
        enc = abi_encode(["address", "bytes32"], [
            "0x" + "11" * 20, "0x" + "22" * 32])
        assert enc[:32] == b"\x00" * 12 + b"\x11" * 20
        assert enc[32:64] == b"\x22" * 32

    def test_dynamic_bytes_layout(self):
        enc = abi_encode(["uint256", "bytes"], [5, b"\xaa\xbb"])
        assert enc[0:32] == (5).to_bytes(32, "big")
        assert enc[32:64] == (0x40).to_bytes(32, "big")   # offset
        assert enc[64:96] == (2).to_bytes(32, "big")      # length
        assert enc[96:98] == b"\xaa\xbb"
        assert len(enc) == 128


class TestAbiTypeDispatch:
    def test_string_is_utf8_even_when_hexlike(self):
        # ethers defaultAbiCoder: string is always utf-8 text
        enc = abi_encode(["string"], ["0xabab"])
        assert enc[32:64] == (6).to_bytes(32, "big")  # 6 chars, not 2 bytes
        assert enc[64:70] == b"0xabab"

    def test_bytes_rejects_non_hex_string(self):
        with pytest.raises(ValueError):
            abi_encode(["bytes"], ["QmNotHex"])

    def test_uint8_range_check(self):
        with pytest.raises(ValueError):
            abi_encode(["uint8"], [300])
        with pytest.raises(ValueError):
            abi_encode(["uint256"], [-1])


class TestDirectoryGuards:
    def test_slash_in_name_rejected(self):
        with pytest.raises(ValueError):
            dag_of_directory({"a/b.png": b"x"})


def _parse_pbnode(block: bytes):
    """Minimal dag-pb reader: → (links [(cid, name, tsize)], data bytes)."""
    links, data, i = [], b"", 0
    while i < len(block):
        tag = block[i]
        i += 1
        ln, used = decode_varint(block[i:])
        i += used
        payload = block[i:i + ln]
        i += ln
        if tag == 0x0A:
            data = payload
        elif tag == 0x12:
            cid, name, tsize, j = b"", "", 0, 0
            while j < len(payload):
                t2 = payload[j]
                j += 1
                if t2 == 0x18:
                    tsize, used = decode_varint(payload[j:])
                    j += used
                    continue
                l2, used = decode_varint(payload[j:])
                j += used
                if t2 == 0x0A:
                    cid = payload[j:j + l2]
                elif t2 == 0x12:
                    name = payload[j:j + l2].decode()
                j += l2
            links.append((cid, name, tsize))
    return links, data


class TestHamtSharding:
    """kubo auto-shards >256 KiB directory blocks into a murmur3/fanout-256
    HAMT (go-unixfs); the sharded root must be deterministic and every
    entry reachable through hex-prefixed shard links."""

    def test_murmur3_reference_vectors(self):
        from arbius_tpu.l0.murmur3 import hamt_hash, murmur3_x64_128

        assert murmur3_x64_128(b"") == (0, 0)
        # the mmh3 library's documented hash64 vector (signed pair)
        h1, h2 = murmur3_x64_128(b"foo")
        assert h1 == (-2129773440516405919) % 2**64
        assert h2 == 9128664383759220103
        assert hamt_hash("foo") == h1.to_bytes(8, "big")

    def test_small_directory_stays_flat(self):
        blocks = {}
        node = dag_of_directory({"out-1.png": b"x"},
                                sink=lambda c, b: blocks.update({c: b}))
        _, data = _parse_pbnode(blocks[node.cid])
        assert data == b"\x08\x01"  # plain UnixFS Directory

    def test_oversized_directory_shards_and_walks(self):
        files = {f"f{i:05d}.bin": bytes([i % 256]) for i in range(6000)}
        blocks = {}
        node = dag_of_directory(files, sink=lambda c, b: blocks.update({c: b}))
        root_links, root_data = _parse_pbnode(blocks[node.cid])
        # UnixFS: Type=5, bitfield, hashType=0x22 murmur3, fanout=256
        assert root_data.startswith(b"\x08\x05")
        assert root_data.endswith(b"\x28\x22\x30\x80\x02")
        assert len(blocks[node.cid]) <= CHUNK_SIZE

        # walk the shard tree: every entry name must be reachable exactly
        # once under its 2-hex-uppercase slot prefixes
        found = {}

        def walk(cid):
            links, data = _parse_pbnode(blocks[cid])
            assert data.startswith(b"\x08\x05")
            for child_cid, name, _ in links:
                prefix, entry = name[:2], name[2:]
                assert prefix == prefix.upper() and len(prefix) == 2
                int(prefix, 16)
                if entry:
                    found[entry] = child_cid
                else:
                    walk(child_cid)

        walk(node.cid)
        assert set(found) == set(files)
        # deterministic
        again = dag_of_directory(files)
        assert again.cid == node.cid and again.tsize == node.tsize

    def test_shard_trigger_is_kubo_estimate_not_block_size(self):
        """kubo shards on Σ(len(name)+len(cid)) > 256 KiB — NOT on the
        serialized block length, which is ~8-12 bytes/link larger. A
        directory in between must stay flat (daemon parity)."""
        # 5500 entries × (10-byte name + 34-byte cid) = 242 KB estimate
        # (< 262144) but a ~300 KB serialized block (> 262144)
        files = {f"g{i:05d}.bin": b"x" for i in range(5500)}
        blocks = {}
        node = dag_of_directory(files, sink=lambda c, b: blocks.update({c: b}))
        _, data = _parse_pbnode(blocks[node.cid])
        assert data == b"\x08\x01"          # flat UnixFS Directory
        assert len(blocks[node.cid]) > CHUNK_SIZE  # block itself is larger

    def test_shard_assignment_matches_name_hash(self):
        from arbius_tpu.l0.murmur3 import hamt_hash

        files = {f"f{i:05d}.bin": b"x" for i in range(6000)}
        blocks = {}
        node = dag_of_directory(files, sink=lambda c, b: blocks.update({c: b}))
        links, _ = _parse_pbnode(blocks[node.cid])
        for _, name, _ in links:
            if len(name) > 2:  # direct entry: prefix must be hash byte 0
                assert int(name[:2], 16) == hamt_hash(name[2:])[0]


class TestCommitment:
    def test_commitment_known_shape(self):
        c = generate_commitment_hex(
            "0x" + "ab" * 20, "0x" + "cd" * 32,
            "0x1220" + "ee" * 32)
        assert c.startswith("0x") and len(c) == 66

    def test_commitment_matches_manual_abi_keccak(self):
        addr = "0x" + "01" * 20
        taskid = "0x" + "02" * 32
        cid = "0x1220" + "03" * 32
        manual = keccak256_hex(
            abi_encode(["address", "bytes32", "bytes"], [addr, taskid, cid]))
        assert generate_commitment_hex(addr, taskid, cid) == manual

    def test_sensitivity(self):
        base = generate_commitment_hex("0x" + "01" * 20, "0x" + "02" * 32, "0x03")
        assert base != generate_commitment_hex("0x" + "01" * 20, "0x" + "02" * 32, "0x04")
        assert base != generate_commitment_hex("0x" + "11" * 20, "0x" + "02" * 32, "0x03")


class TestSeed:
    def test_modulus(self):
        # miner/src/utils.ts:15-19
        assert taskid2seed("0x00") == 0
        assert taskid2seed("0x1FFFFFFFFFFFF0") == 0
        assert taskid2seed("0x1FFFFFFFFFFFF1") == 1
        big = "0x" + "ff" * 32
        assert taskid2seed(big) == int(big, 16) % 0x1FFFFFFFFFFFF0

    def test_accepts_bytes_and_int(self):
        assert taskid2seed(b"\x01\x00") == 256
        assert taskid2seed(256) == 256
