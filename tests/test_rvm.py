"""RVM family tests: published-topology shapes, recurrence semantics,
determinism, the downsample+refine path, and output_type enum parity with
templates/robust_video_matting.json."""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from arbius_tpu.models.rvm import (
    ConvGRU,
    OUTPUT_TYPES,
    RVMConfig,
    RVMPipeline,
    RVMPipelineConfig,
    MattingStep,
)

pytestmark = [pytest.mark.slow, pytest.mark.model]


def synth_video(t=4, h=32, w=32, seed=0):
    rng = np.random.default_rng(seed)
    base = rng.integers(0, 255, (1, h, w, 3))
    drift = rng.integers(-10, 10, (t, 1, 1, 3))
    return np.clip(base + drift, 0, 255).astype(np.uint8)


def test_convgru_state_update():
    cell = ConvGRU(channels=4)
    h = jnp.zeros((1, 8, 8, 4))
    x = jnp.ones((1, 8, 8, 4))
    params = cell.init(jax.random.PRNGKey(0), x, h)["params"]
    h1 = cell.apply({"params": params}, x, h)
    h2 = cell.apply({"params": params}, x, h1)
    assert h1.shape == (1, 8, 8, 4)
    assert not np.array_equal(np.asarray(h1), np.asarray(h2))  # evolving


def test_matting_step_shapes():
    cfg = RVMConfig.tiny()
    step = MattingStep(cfg)
    frame = jnp.zeros((1, 32, 32, 3))
    rec = step.init_rec(1, 32, 32)
    params = step.init(jax.random.PRNGKey(0), frame, rec)["params"]
    fgr, pha, new_rec = step.apply({"params": params}, frame, rec)
    assert pha.shape == (1, 32, 32, 1)
    assert fgr.shape == (1, 32, 32, 3)
    assert len(new_rec) == 4
    # states sit at 1/2..1/16 with half of each stage's channels
    assert new_rec[0].shape == (1, 16, 16, cfg.dec_ch[2] // 2)
    assert new_rec[3].shape == (1, 2, 2, cfg.aspp_ch // 2)
    assert float(pha.min()) >= 0.0 and float(pha.max()) <= 1.0


def test_full_config_pyramid_channels():
    """The default config is the published rvm_mobilenetv3: taps must give
    16/24/40ch features and a 960ch final conv (f4 at 1/16 via dilation)."""
    cfg = RVMConfig()
    t1, t2, t3 = cfg.taps
    assert cfg.ir_rows[t1 - 1][3] == 16
    assert cfg.ir_rows[t2 - 1][3] == 24
    assert cfg.ir_rows[t3 - 1][3] == 40
    assert cfg.last_ch == 960 and cfg.aspp_ch == 128
    assert cfg.dec_ch == (80, 40, 32) and cfg.out_ch == 16
    # dilated last stage: rows 13-15 carry dilation 2 ⇒ effective stride 1
    assert all(r[7] == 2 for r in cfg.ir_rows[12:])


def test_recurrence_carries_across_frames():
    """The same frame at t=0 and t=3 must matte differently — the GRU
    state is genuinely temporal (stream semantics, not per-frame)."""
    pipe = RVMPipeline(RVMPipelineConfig.tiny())
    params = pipe.init_params(height=32, width=32)
    frame = synth_video(1, seed=3)[0]
    video = np.stack([frame] * 4)
    out = pipe.matte(params, video, output_type="alpha-mask")
    assert not np.array_equal(out[0], out[3])


def test_downsample_refine_path():
    """Frames above the published 512px rule run the downsample+refine
    path: base_hw snaps to the granule and matte still produces full-res
    deterministic bytes through the guided-filter refiner."""
    pipe = RVMPipeline(RVMPipelineConfig(
        model=RVMConfig.tiny(), auto_downsample_px=24))
    assert pipe.base_hw(64, 48) == (32, 16)
    assert pipe.base_hw(16, 16) is None
    params = pipe.init_params(height=64, width=48)
    video = synth_video(2, 64, 48)
    a = pipe.matte(params, video, output_type="green-screen")
    assert a.shape == video.shape
    np.testing.assert_array_equal(a, pipe.matte(params, video.copy(),
                                                output_type="green-screen"))


def test_matte_deterministic_and_types():
    pipe = RVMPipeline(RVMPipelineConfig.tiny())
    params = pipe.init_params(height=32, width=32)
    video = synth_video()
    for ot in OUTPUT_TYPES:
        a = pipe.matte(params, video, output_type=ot)
        b = pipe.matte(params, video.copy(), output_type=ot)
        assert a.shape == video.shape and a.dtype == np.uint8
        np.testing.assert_array_equal(a, b)


def test_foreground_mask_is_binary():
    pipe = RVMPipeline(RVMPipelineConfig.tiny())
    params = pipe.init_params(height=32, width=32)
    out = pipe.matte(params, synth_video(), output_type="foreground-mask")
    assert set(np.unique(out)) <= {0, 255}


def test_invalid_inputs_rejected():
    pipe = RVMPipeline(RVMPipelineConfig.tiny())
    params = pipe.init_params(height=32, width=32)
    with pytest.raises(ValueError, match="output_type"):
        pipe.matte(params, synth_video(), output_type="sepia")
    with pytest.raises(ValueError, match="multiples"):
        pipe.matte(params, synth_video(h=30), output_type="alpha-mask")


def test_matted_video_to_mp4():
    from arbius_tpu.codecs import encode_mp4

    pipe = RVMPipeline(RVMPipelineConfig.tiny())
    params = pipe.init_params(height=32, width=32)
    out = pipe.matte(params, synth_video(), output_type="green-screen")
    mp4 = encode_mp4(out, fps=8)
    assert mp4[4:8] == b"ftyp" and encode_mp4(out, fps=8) == mp4


def test_probe_clip_deterministic_and_golden_recordable():
    """File-input golden path: the probe clip is bit-deterministic
    (platform-independent integer ops) and `record-golden --probe-video`
    produces a stable CID for the tiny RVM end-to-end."""
    import json

    from arbius_tpu.codecs import encode_mp4
    from arbius_tpu.codecs.probe import probe_clip

    a, b = probe_clip(4, 32, 32), probe_clip(4, 32, 32)
    np.testing.assert_array_equal(a, b)
    assert a.shape == (4, 32, 32, 3) and a.dtype == np.uint8
    assert encode_mp4(a, fps=8) == encode_mp4(b, fps=8)

    import contextlib
    import io

    from arbius_tpu.cli import main

    runs = []
    for _ in range(2):
        buf = io.StringIO()
        with contextlib.redirect_stdout(buf):
            assert main(["record-golden", "--template",
                         "robust_video_matting", "--tiny",
                         "--probe-video", "4x32x32"]) == 0
        runs.append(json.loads(buf.getvalue().strip()))
    assert runs[0]["golden"]["cid"] == runs[1]["golden"]["cid"]
    assert runs[0]["golden"]["input"]["input_video"].startswith("Qm")


def test_boot_self_test_with_probe_golden_and_no_store():
    """Self-contained file-input golden: a ModelConfig.golden carrying
    probe_video boots a node with NO content store — the factory
    synthesizes the pinned clip for its own CID at boot. Wrong-CID
    goldens still fail loudly (BootError, not a crash)."""
    import contextlib
    import io
    import json

    import pytest

    from arbius_tpu.chain import Engine, TokenLedger, WAD
    from arbius_tpu.cli import main
    from arbius_tpu.node import (
        BootError,
        LocalChain,
        MinerNode,
        MiningConfig,
        ModelConfig,
    )
    from arbius_tpu.node.factory import build_registry

    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        assert main(["record-golden", "--template", "robust_video_matting",
                     "--tiny", "--probe-video", "4x32x32"]) == 0
    rec = json.loads(buf.getvalue().strip())
    assert rec["golden"]["probe_video"] == "4x32x32"

    tok = TokenLedger()
    eng = Engine(tok, start_time=10_000)
    tok.mint(Engine.ADDRESS, 600_000 * WAD)
    miner = "0x" + "aa" * 20
    tok.mint(miner, 1_000 * WAD)
    tok.approve(miner, Engine.ADDRESS, 10**30)
    mid = "0x" + eng.register_model(miner, miner, 0, b'{"m":1}').hex()

    def world(golden):
        cfgm = ModelConfig(id=mid, template="robust_video_matting",
                           tiny=True, golden=golden)
        cfg = MiningConfig(models=(cfgm,))
        # no resolve_file, no store: the probe golden is all it has
        return MinerNode(LocalChain(eng, miner), cfg, build_registry(cfg))

    world(rec["golden"]).boot()  # green

    bad = dict(rec["golden"], cid="0x1220" + "ab" * 32)
    with pytest.raises(BootError, match="self-test failed"):
        world(bad).boot()
