"""RVM family tests: recurrence semantics, determinism, output_type enum
parity with templates/robust_video_matting.json."""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from arbius_tpu.models.rvm import (
    ConvGRUCell,
    OUTPUT_TYPES,
    RVMConfig,
    RVMPipeline,
    RVMPipelineConfig,
    RVMStep,
)

pytestmark = [pytest.mark.slow, pytest.mark.model]


def synth_video(t=4, h=32, w=32, seed=0):
    rng = np.random.default_rng(seed)
    base = rng.integers(0, 255, (1, h, w, 3))
    drift = rng.integers(-10, 10, (t, 1, 1, 3))
    return np.clip(base + drift, 0, 255).astype(np.uint8)


def test_convgru_state_update():
    cell = ConvGRUCell(channels=4)
    h = jnp.zeros((1, 8, 8, 4))
    x = jnp.ones((1, 8, 8, 4))
    params = cell.init(jax.random.PRNGKey(0), h, x)["params"]
    h1 = cell.apply({"params": params}, h, x)
    h2 = cell.apply({"params": params}, h1, x)
    assert h1.shape == (1, 8, 8, 4)
    assert not np.array_equal(np.asarray(h1), np.asarray(h2))  # evolving


def test_rvm_step_shapes():
    cfg = RVMConfig.tiny()
    step = RVMStep(cfg)
    frame = jnp.zeros((1, 32, 32, 3))
    states = step.init_states(1, 32, 32)
    params = step.init(jax.random.PRNGKey(0), frame, states)["params"]
    alpha, fgr, new_states = step.apply({"params": params}, frame, states)
    assert alpha.shape == (1, 32, 32, 1)
    assert fgr.shape == (1, 32, 32, 3)
    assert len(new_states) == len(cfg.dec_channels)
    assert float(alpha.min()) >= 0.0 and float(alpha.max()) <= 1.0


def test_recurrence_carries_across_frames():
    """The same frame at t=0 and t=3 must matte differently — the GRU
    state is genuinely temporal (stream semantics, not per-frame)."""
    pipe = RVMPipeline(RVMPipelineConfig.tiny())
    params = pipe.init_params(height=32, width=32)
    frame = synth_video(1, seed=3)[0]
    video = np.stack([frame] * 4)
    out = pipe.matte(params, video, output_type="alpha-mask")
    assert not np.array_equal(out[0], out[3])


def test_matte_deterministic_and_types():
    pipe = RVMPipeline(RVMPipelineConfig.tiny())
    params = pipe.init_params(height=32, width=32)
    video = synth_video()
    for ot in OUTPUT_TYPES:
        a = pipe.matte(params, video, output_type=ot)
        b = pipe.matte(params, video.copy(), output_type=ot)
        assert a.shape == video.shape and a.dtype == np.uint8
        np.testing.assert_array_equal(a, b)


def test_foreground_mask_is_binary():
    pipe = RVMPipeline(RVMPipelineConfig.tiny())
    params = pipe.init_params(height=32, width=32)
    out = pipe.matte(params, synth_video(), output_type="foreground-mask")
    assert set(np.unique(out)) <= {0, 255}


def test_invalid_inputs_rejected():
    pipe = RVMPipeline(RVMPipelineConfig.tiny())
    params = pipe.init_params(height=32, width=32)
    with pytest.raises(ValueError, match="output_type"):
        pipe.matte(params, synth_video(), output_type="sepia")
    with pytest.raises(ValueError, match="multiples"):
        pipe.matte(params, synth_video(h=30), output_type="alpha-mask")


def test_matted_video_to_mp4():
    from arbius_tpu.codecs import encode_mp4

    pipe = RVMPipeline(RVMPipelineConfig.tiny())
    params = pipe.init_params(height=32, width=32)
    out = pipe.matte(params, synth_video(), output_type="green-screen")
    mp4 = encode_mp4(out, fps=8)
    assert mp4[4:8] == b"ftyp" and encode_mp4(out, fps=8) == mp4
