"""AOT executable cache (docs/compile-cache.md) acceptance suite.

The non-negotiable is determinism: a disk-hit dispatch must produce
byte-identical results to a fresh-compile dispatch (pinned here for
the image probe mesh-off and dp2, the video-shaped seq probe, and a
real tiny SD-1.5 through solve_cid_batch), a corrupted or
wrong-environment entry must fall back to compile with a journaled
`aot_cache_reject` (never an error, never wrong bytes), and a drifted
program — the injected bf16-GroupNorm regression — must MISS, never
load stale. The fleet half: a 4-worker fleet over ONE shared cache
directory holds every SIM1xx invariant with zero rejects.
"""
from __future__ import annotations

import json
import math
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# fixed synthetic environment for entry-format tests: key derivation is
# pure over these, so goldens cannot depend on the host's jaxlib
FIXED_ENV = {"jax": "0.0-fixture", "jaxlib": "0.0-fixture",
             "platform": "cpu", "device_kind": "fixture-cpu",
             "device_count": 1}


def _write_fixture(cache_dir, program, arg_sig, payload, *, tag=None,
                   env=None, key=None):
    from arbius_tpu.aotcache import derive_key, make_header, write_entry

    env = env if env is not None else FIXED_ENV
    real_key = derive_key(program, env, arg_sig, "")
    key = key if key is not None else real_key
    return key, write_entry(
        cache_dir, key,
        make_header(key, program, env, arg_sig, payload, tag=tag),
        payload)


# -- entry format + key derivation ------------------------------------------

def test_entry_roundtrip_and_key_determinism(tmp_path):
    from arbius_tpu.aotcache import derive_key, read_entry, read_header

    payload = b"payload-bytes" * 100
    key, path = _write_fixture(str(tmp_path), "sha256:prog", "argsig",
                               payload, tag="sd15.1.64.64.2.DDIM")
    header, view, closer = read_entry(path)
    assert bytes(view) == payload
    closer()
    assert header["key"] == key
    assert header["tag"] == "sd15.1.64.64.2.DDIM"
    assert header["payload_len"] == len(payload)
    # pure + deterministic: same components → same key, any component
    # moves it — program (the graphlint fingerprint), environment
    # (jaxlib/platform/device), argument signature
    assert derive_key("sha256:prog", FIXED_ENV, "argsig") == key
    assert derive_key("sha256:DRIFT", FIXED_ENV, "argsig") != key
    assert derive_key("sha256:prog", dict(FIXED_ENV, jaxlib="9.9"),
                      "argsig") != key
    assert derive_key("sha256:prog", dict(FIXED_ENV, platform="tpu"),
                      "argsig") != key
    assert derive_key("sha256:prog", FIXED_ENV, "other") != key
    assert derive_key("sha256:prog", FIXED_ENV, "argsig", "donate") != key
    # header-only read is digest-checked too
    assert read_header(path)["key"] == key


def test_corrupt_truncated_and_doctored_entries_reject(tmp_path):
    from arbius_tpu.aotcache import CacheReject, read_entry, read_header

    payload = b"x" * 4096

    def reason_of(mutate, name, reader=read_header):
        d = tmp_path / name
        d.mkdir()
        _, path = _write_fixture(str(d), "sha256:p", "a", payload)
        mutate(path)
        with pytest.raises(CacheReject) as e:
            out = reader(path)
            if reader is read_entry:  # pragma: no cover — must raise
                out[2]()
        return e.value.reason

    def truncate(p):
        with open(p, "r+b") as f:
            f.truncate(os.path.getsize(p) - 100)

    def flip_payload(p):
        blob = bytearray(open(p, "rb").read())
        blob[-1] ^= 0xFF
        open(p, "wb").write(bytes(blob))

    def smash_magic(p):
        blob = bytearray(open(p, "rb").read())
        blob[0] = 0x00
        open(p, "wb").write(bytes(blob))

    from arbius_tpu.aotcache import read_entry

    assert reason_of(truncate, "t") == "truncated"
    # a bit-flip keeps the length: only the FULL (load-path / --verify)
    # read hashes the payload — the cheap header scan deliberately
    # doesn't (docs/compile-cache.md)
    assert reason_of(flip_payload, "f", reader=read_entry) == \
        "payload_digest_mismatch"
    assert reason_of(smash_magic, "m") == "bad_magic"


def test_concurrent_two_process_write_same_key(tmp_path):
    """tmp+rename under a real two-OS-process race: last-writer-wins,
    the surviving entry is whole (one writer's bytes, never torn), and
    both writers succeed."""
    from arbius_tpu.aotcache import derive_key, entry_path, read_entry

    key = derive_key("sha256:race", FIXED_ENV, "a")
    script = (
        "import sys\n"
        f"sys.path.insert(0, {REPO!r})\n"
        "from arbius_tpu.aotcache import make_header, write_entry\n"
        "key, d, marker = sys.argv[1], sys.argv[2], sys.argv[3]\n"
        f"env = {FIXED_ENV!r}\n"
        "payload = marker.encode() * 4096\n"
        "for _ in range(30):\n"
        "    write_entry(d, key, make_header(key, 'sha256:race', env,"
        " 'a', payload, tag=marker), payload)\n")
    procs = [subprocess.Popen(
        [sys.executable, "-c", script, key, str(tmp_path), marker])
        for marker in ("AAAA", "BBBB")]
    for p in procs:
        assert p.wait(timeout=120) == 0
    header, view, closer = read_entry(entry_path(str(tmp_path), key))
    blob = bytes(view)
    closer()
    assert blob in (b"AAAA" * 4096, b"BBBB" * 4096), "torn entry"
    assert header["tag"] in ("AAAA", "BBBB")
    assert header["key"] == key


# -- the jit_cache_get disk tier --------------------------------------------

def _dispatch_probe(probe_cls, aot_dir, **probe_kw):
    """One probe life: dispatch twice under a fresh Obs (+ optional AOT
    cache); returns (bytes, obs)."""
    import numpy as np

    from arbius_tpu.aotcache import AotCache
    from arbius_tpu.obs import Obs, use_obs

    obs = Obs(journal_capacity=256)
    if aot_dir is not None:
        obs.aot_cache = AotCache(aot_dir)
    probe = probe_cls(**probe_kw)
    items = [({"prompt": "aot x"}, 7), ({"prompt": "aot y"}, 8)]
    with use_obs(obs):
        out = np.asarray(probe.dispatch(items)).tobytes()
        np.asarray(probe.dispatch(items))  # memory-tier hit
    return out, obs


def _counters(obs):
    reg = obs.registry
    return {
        "mem_hits": reg.counter("arbius_jit_cache_hits_total",
                                labelnames=("tier",)).value(tier="memory"),
        "disk_hits": reg.counter("arbius_jit_cache_hits_total",
                                 labelnames=("tier",)).value(tier="disk"),
        "misses": reg.counter("arbius_jit_cache_misses_total").value(),
        "loads": reg.counter("arbius_aot_cache_loads_total").value(),
        "writes": reg.counter("arbius_aot_cache_writes_total").value(),
        "rejects": reg.counter("arbius_aot_cache_rejects_total").value(),
        "compiles": reg.histogram("arbius_compile_seconds").count(),
        "load_obs": reg.histogram("arbius_aot_load_seconds").count(),
    }


def test_image_probe_disk_tier_bytes_and_metrics(tmp_path):
    """The whole tier story on the image probe: cache-off == cold-write
    == warm-load bytes; hits split by tier; compile recorded on the
    miss life, load seconds on the hit life; warm set fed either way."""
    from arbius_tpu.parallel.meshsolve import ShardedImageProbe

    d = str(tmp_path / "cache")
    off, _ = _dispatch_probe(ShardedImageProbe, None)
    cold, obs_cold = _dispatch_probe(ShardedImageProbe, d)
    warm, obs_warm = _dispatch_probe(ShardedImageProbe, d)
    assert off == cold == warm
    c = _counters(obs_cold)
    assert c["misses"] == 1 and c["writes"] == 1 and c["compiles"] == 1
    assert c["disk_hits"] == 0 and c["mem_hits"] == 1
    w = _counters(obs_warm)
    assert w["disk_hits"] == 1 and w["loads"] == 1 and w["load_obs"] == 1
    assert w["misses"] == 0 and w["compiles"] == 0 and w["rejects"] == 0
    assert w["mem_hits"] == 1
    # the loaded executable is warm THIS life too (packer signal)
    assert "meshprobe.img.b2" in obs_warm.jit_warm
    h = obs_warm.registry.histogram("arbius_aot_load_seconds")
    assert h.recent()[0][0] == "meshprobe.img.b2"


def test_seq_probe_video_shaped_disk_tier_bytes(tmp_path):
    from arbius_tpu.parallel.meshsolve import ShardedSeqProbe

    d = str(tmp_path / "cache")
    off, _ = _dispatch_probe(ShardedSeqProbe, None, frames=4)
    cold, _ = _dispatch_probe(ShardedSeqProbe, d, frames=4)
    warm, obs_warm = _dispatch_probe(ShardedSeqProbe, d, frames=4)
    assert off == cold == warm
    w = _counters(obs_warm)
    assert w["disk_hits"] == 1 and w["rejects"] == 0


def test_dp2_mesh_disk_tier_bytes(tmp_path):
    """Meshed program through the disk tier on the 8-way CPU harness:
    dp2 bytes are identical across compile and deserialize lives (and,
    per the meshsolve pins, to mesh-off)."""
    from arbius_tpu.parallel import meshsolve
    from arbius_tpu.parallel.meshsolve import ShardedImageProbe

    mesh = meshsolve.boot_mesh({"dp": 2})
    d = str(tmp_path / "cache")
    off, _ = _dispatch_probe(ShardedImageProbe, None, mesh=mesh)
    cold, _ = _dispatch_probe(ShardedImageProbe, d, mesh=mesh)
    warm, obs_warm = _dispatch_probe(ShardedImageProbe, d, mesh=mesh)
    assert off == cold == warm
    w = _counters(obs_warm)
    assert w["disk_hits"] == 1 and w["rejects"] == 0


def test_corrupt_entry_falls_back_to_compile(tmp_path):
    """A truncated entry journals `aot_cache_reject`, the dispatch
    compiles fresh (same bytes), and a good entry is re-published."""
    from arbius_tpu.aotcache.store import scan
    from arbius_tpu.parallel.meshsolve import ShardedImageProbe

    d = str(tmp_path / "cache")
    cold, _ = _dispatch_probe(ShardedImageProbe, d)
    (key, path, size), = scan(d)
    with open(path, "r+b") as f:
        f.truncate(size // 2)
    again, obs = _dispatch_probe(ShardedImageProbe, d)
    assert again == cold
    c = _counters(obs)
    assert c["rejects"] == 1 and c["disk_hits"] == 0 and c["writes"] == 1
    (ev,) = obs.journal.events(kind="aot_cache_reject")
    assert ev["reason"] == "truncated" and ev["key"] == key
    # the rewrite healed the cache: next life disk-hits again
    healed, obs2 = _dispatch_probe(ShardedImageProbe, d)
    assert healed == cold and _counters(obs2)["disk_hits"] == 1


def test_wrong_environment_entry_rejects_not_loads(tmp_path):
    """An entry whose header claims another environment under the key
    this process would look up must reject (env_mismatch), never
    deserialize — and the boot warm scan must exclude it."""
    import jax.numpy as jnp

    import jax

    from arbius_tpu.aotcache import AotCache
    from arbius_tpu.obs import Obs, use_obs

    d = str(tmp_path / "cache")
    obs = Obs(journal_capacity=64)
    cache = AotCache(d)
    obs.aot_cache = cache
    jfn = jax.jit(lambda x: x + 1.0)
    args = (jnp.ones((4,)),)
    key = cache.key_for(jfn, args)
    # doctored file AT the real key, claiming a foreign environment
    _write_fixture(d, "sha256:foreign", "a", b"Z" * 256,
                   env=dict(FIXED_ENV, platform="tpu"), key=key,
                   tag="foreign.tag")
    assert cache.tags() == frozenset()  # warm scan: env-filtered
    with use_obs(obs):
        assert cache.load(key, tag="t") is None
    (ev,) = obs.journal.events(kind="aot_cache_reject")
    assert ev["reason"] == "env_mismatch"


def test_layout_mismatched_entries_are_not_disk_warm(tmp_path):
    """Differently-laid-out workers sharing one directory: a dp2
    worker's entries are real executables a single-device worker
    cannot load (different fingerprint ⇒ different key), so the warm
    scan must filter on the writer's layout stamp — otherwise the
    packer would warm-boost exactly the buckets it cannot load."""
    from arbius_tpu.aotcache import (
        AotCache,
        derive_key,
        env_signature,
        make_header,
        write_entry,
    )

    d = str(tmp_path / "shared")
    env = env_signature()
    for layout, tag in (("single", "sd15.single-tag"),
                        ("dp2", "sd15.dp2-tag")):
        key = derive_key("sha256:" + tag, env, "a")
        write_entry(d, key, make_header(key, "sha256:" + tag, env, "a",
                                        b"P" * 32, tag=tag,
                                        layout=layout), b"P" * 32)
    assert AotCache(d).tags() == frozenset({"sd15.single-tag"})
    assert AotCache(d, layout="dp2").tags() == \
        frozenset({"sd15.dp2-tag"})


def test_lru_eviction_under_max_bytes(tmp_path):
    """Budget fits one entry: publishing a second evicts the older
    (mtime) one, keeps the just-written one, counts + journals it."""
    import jax.numpy as jnp

    import jax

    from arbius_tpu.aotcache import AotCache
    from arbius_tpu.aotcache.store import scan, total_bytes
    from arbius_tpu.obs import Obs, use_obs

    d = str(tmp_path / "cache")
    obs = Obs(journal_capacity=64)
    cache = AotCache(d)
    obs.aot_cache = cache
    args = (jnp.ones((4,)),)
    with use_obs(obs):
        cache.get_or_compile(lambda: jax.jit(lambda x: x + 1.0),
                             lambda: args, tag="t1")
        (k1, p1, _), = scan(d)
        os.utime(p1, (1, 1))  # decisively the LRU entry
        cache.max_bytes = total_bytes(d) + 16
        cache.get_or_compile(lambda: jax.jit(lambda x: x * 3.0),
                             lambda: args, tag="t2")
    keys = [k for k, _, _ in scan(d)]
    assert k1 not in keys and len(keys) == 1
    reg = obs.registry
    assert reg.counter("arbius_aot_cache_evictions_total").value() == 1
    (ev,) = obs.journal.events(kind="aot_cache_evict")
    assert ev["keys"] == [k1]
    # tags() now only knows the survivor
    assert cache.tags() == frozenset({"t2"})


def test_key_derivation_failure_degrades_to_lazy_path(tmp_path):
    """The cache must never be WHY a solve fails: an args thunk that
    raises degrades to the exact pre-AOT contract (lazy jitted fn,
    warm=False so the dispatch times the first call), with a journaled
    `aot_cache_skip` — and nothing is written."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from arbius_tpu.aotcache import AotCache
    from arbius_tpu.aotcache.store import scan
    from arbius_tpu.obs import Obs, jit_cache_get, use_obs

    d = str(tmp_path / "cache")
    obs = Obs(journal_capacity=64)
    obs.aot_cache = AotCache(d)

    def boom():
        raise RuntimeError("no args for you")

    with use_obs(obs):
        fn, warm, tag = jit_cache_get(
            {}, 1, lambda: jax.jit(lambda x: x + 1.0), tag="t",
            aot_args=boom)
    assert not warm, "fallback must keep the lazy-path timing contract"
    assert np.asarray(fn(jnp.ones((2,)))).tolist() == [2.0, 2.0]
    (ev,) = obs.journal.events(kind="aot_cache_skip")
    assert ev["reason"].startswith("key_derivation: RuntimeError")
    assert obs.registry.counter(
        "arbius_aot_cache_skips_total").value() == 1
    assert scan(d) == []
    assert "t" in obs.jit_warm  # compiles at first dispatch, like pre-AOT


def test_store_write_failure_does_not_fail_the_solve(tmp_path):
    """An unwritable shared cache path (here: a plain file squatting on
    the directory name — chmod tricks don't bind under root): the
    compile succeeds, the publish skips with a journaled reason, the
    dispatch result stands."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from arbius_tpu.aotcache import AotCache
    from arbius_tpu.obs import Obs, jit_cache_get, use_obs

    d = tmp_path / "not-a-dir"
    d.write_bytes(b"squatter")
    obs = Obs(journal_capacity=64)
    obs.aot_cache = AotCache(str(d))
    with use_obs(obs):
        fn, warm, _ = jit_cache_get(
            {}, 1, lambda: jax.jit(lambda x: x * 2.0), tag="t",
            aot_args=lambda: (jnp.ones((2,)),))
    assert warm  # compiled eagerly — the write was what failed
    assert np.asarray(fn(jnp.ones((2,)))).tolist() == [2.0, 2.0]
    (ev,) = obs.journal.events(kind="aot_cache_skip")
    assert ev["reason"].startswith("write:")
    assert obs.registry.counter(
        "arbius_aot_cache_skips_total").value() == 1


# -- drift = miss, never stale (the invalidation-by-construction pin) -------

def _sd15_abstract_bucket(pipe):
    """(jitted bucket fn, abstract args) — key derivation needs only
    avals, so no params materialize and nothing compiles."""
    import jax
    import jax.numpy as jnp

    sds = jax.ShapeDtypeStruct
    shapes = jax.eval_shape(pipe._init_fn(8, 8), jax.random.PRNGKey(0))
    length = pipe.config.text.max_length
    args = (shapes, sds((1, length), jnp.int32), sds((1, length), jnp.int32),
            sds((1,), jnp.float32), sds((1,), jnp.uint32),
            sds((1,), jnp.uint32))
    return pipe._build_bucket(1, 64, 64, 2, "DDIM"), args


def test_drifted_bf16_groupnorm_program_misses_never_stale(
        tmp_path, monkeypatch):
    """The acceptance pin: the injected bf16-GroupNorm regression (the
    same perturbation test_graphlint drives through the golden gate)
    hashes to a DIFFERENT cache key with identical env/arg signatures —
    so a cache populated by the clean program answers the drifted one
    with a plain miss, never a stale load, never a reject."""
    import flax.linen as nn
    import jax
    import jax.numpy as jnp

    from arbius_tpu.aotcache import AotCache, args_signature
    from arbius_tpu.models.sd15 import SD15Config, SD15Pipeline
    from arbius_tpu.obs import Obs, use_obs

    cache = AotCache(str(tmp_path / "cache"))
    clean_pipe = SD15Pipeline(SD15Config.tiny())
    clean_fn, clean_args = _sd15_abstract_bucket(clean_pipe)
    clean_key = cache.key_for(clean_fn, clean_args)

    from arbius_tpu.models import common as common_mod
    from arbius_tpu.models.sd15 import unet as unet_mod
    from arbius_tpu.models.sd15 import vae as vae_mod

    class Bf16StatsGN(nn.Module):
        """GroupNorm statistics in ACTIVATION dtype — the regression
        graphlint's golden gate exists for (test_graphlint)."""
        num_groups: int = 32
        epsilon: float = 1e-5

        @nn.compact
        def __call__(self, x):
            g = math.gcd(x.shape[-1], self.num_groups)
            b, h, w, c = x.shape
            xg = x.reshape(b, h, w, g, c // g)
            n = h * w * (c // g)
            zero = jnp.zeros((), x.dtype)
            s = jax.lax.reduce(xg, zero, jax.lax.add, (1, 2, 4))
            mean = (s / n)[:, None, None, :, None]
            s2 = jax.lax.reduce(xg * xg, zero, jax.lax.add, (1, 2, 4))
            var = (s2 / n)[:, None, None, :, None] - mean * mean
            out = (xg - mean) * jax.lax.rsqrt(var + self.epsilon)
            return out.reshape(b, h, w, c)

    for mod in (common_mod, unet_mod, vae_mod):
        monkeypatch.setattr(mod, "GroupNorm32", Bf16StatsGN)
    drift_pipe = SD15Pipeline(SD15Config.tiny())
    drift_fn, drift_args = _sd15_abstract_bucket(drift_pipe)
    drift_key = cache.key_for(drift_fn, drift_args)

    assert drift_key != clean_key, \
        "a drifted program must hash to a different cache key"
    # the drifted CANONICAL FINGERPRINT alone moves the key: re-derive
    # both keys with the drifted program's own env/arg components and
    # only the program swapped — still different (the GN patch also
    # reshapes the param tree, so the live arg signature moves too;
    # this isolates the fingerprint's contribution)
    from arbius_tpu.aotcache import derive_key
    from arbius_tpu.analysis.graph.fingerprint import fingerprint

    import jax

    fp_clean = fingerprint(jax.make_jaxpr(clean_fn)(*clean_args))
    fp_drift = fingerprint(jax.make_jaxpr(drift_fn)(*drift_args))
    assert fp_clean != fp_drift
    asig = args_signature(drift_args)
    assert derive_key(fp_clean, cache.env(), asig) != \
        derive_key(fp_drift, cache.env(), asig)

    # populate the clean key; the drifted lookup is a PLAIN miss
    _write_fixture(cache.dir, "sha256:whatever", "a", b"W" * 128,
                   env=cache.env(), key=clean_key, tag="clean")
    obs = Obs(journal_capacity=64)
    with use_obs(obs):
        assert cache.load(drift_key, tag="drift") is None
    assert obs.journal.events(kind="aot_cache_reject") == []
    assert obs.registry.counter(
        "arbius_aot_cache_rejects_total").value() == 0


# -- real tiny SD-1.5: CID byte-equality across tiers -----------------------

def test_sd15_cids_identical_cache_off_cold_warm(tmp_path):
    """A real (tiny) SD-1.5 solve through solve_cid_batch: cache-off,
    cold cache (compile+publish), and a fresh warm life (deserialize)
    must emit byte-identical CIDs and files."""
    from arbius_tpu.aotcache import AotCache
    from arbius_tpu.models.sd15 import SD15Config, SD15Pipeline
    from arbius_tpu.node.factory import tiny_byte_tokenizer
    from arbius_tpu.node.solver import (
        ModelRegistry,
        RegisteredModel,
        SD15Runner,
        solve_cid_batch,
    )
    from arbius_tpu.obs import Obs, use_obs
    from arbius_tpu.templates.engine import load_template

    cfg = SD15Config.tiny()
    params = SD15Pipeline(
        cfg, tokenizer=tiny_byte_tokenizer(cfg.text)).init_params(
        seed=0, height=64, width=64)
    tmpl = load_template("anythingv3")
    items = [({"prompt": "aot cat", "negative_prompt": "", "width": 64,
               "height": 64, "num_inference_steps": 2,
               "scheduler": "DDIM", "seed": 7}, 7)]
    d = str(tmp_path / "cache")

    def life(aot: bool):
        pipe = SD15Pipeline(cfg, tokenizer=tiny_byte_tokenizer(cfg.text))
        model = RegisteredModel(id="0x" + "11" * 32, template=tmpl,
                                runner=SD15Runner(pipe, params))
        ModelRegistry().register(model)
        obs = Obs(journal_capacity=64)
        if aot:
            obs.aot_cache = AotCache(d)
        with use_obs(obs):
            out = solve_cid_batch(model, items, canonical_batch=1)
        return out, obs

    off, _ = life(False)
    cold, obs_cold = life(True)
    warm, obs_warm = life(True)
    assert off == cold == warm  # (cid, files) pairs, bytes and all
    assert _counters(obs_cold)["writes"] == 1
    w = _counters(obs_warm)
    assert w["disk_hits"] == 1 and w["compiles"] == 0 and \
        w["rejects"] == 0


# -- cross-life warm boost (scheduler) --------------------------------------

class _TagFakeRunner:
    """Instant fake image runner that exposes the disk-warm join
    surface (`cache_tag`) the real runners defer to their pipelines."""

    def __call__(self, hydrated: dict, seed: int) -> dict:
        import hashlib

        canon = json.dumps({k: v for k, v in hydrated.items()
                            if k != "seed"}, sort_keys=True).encode()
        blob = hashlib.sha256(canon + seed.to_bytes(8, "big")).digest()
        return {"out-1.png": b"\x89PNG" + blob}

    def cache_tag(self, hydrated: dict, batch: int) -> str:
        return f"faketag.b{batch}.w{hydrated.get('width', 512)}"


def _mini_world(tmp_path, *, aot_dir=None, sched_on=True):
    from arbius_tpu.chain import WAD, Engine, TokenLedger
    from arbius_tpu.node import (
        LocalChain,
        MinerNode,
        MiningConfig,
        ModelConfig,
        ModelRegistry,
        RegisteredModel,
    )
    from arbius_tpu.node.config import AotCacheConfig, SchedConfig
    from arbius_tpu.templates.engine import load_template

    tok = TokenLedger()
    eng = Engine(tok, start_time=10_000)
    tok.mint(Engine.ADDRESS, 600_000 * WAD)
    miner, user = "0x" + "aa" * 20, "0x" + "01" * 20
    for a in (miner, user):
        tok.mint(a, 10**6 * WAD)
        tok.approve(a, Engine.ADDRESS, 10**30)
    mid = "0x" + eng.register_model(user, user, 0, b"{}").hex()
    registry = ModelRegistry()
    registry.register(RegisteredModel(
        id=mid, template=load_template("anythingv3"),
        runner=_TagFakeRunner()))
    chain = LocalChain(eng, miner)
    chain.validator_deposit(100 * WAD)
    node = MinerNode(
        chain,
        MiningConfig(models=(ModelConfig(id=mid, template="anythingv3"),),
                     canonical_batch=1, compile_cache_dir=None,
                     sched=SchedConfig(enabled=sched_on)
                     if sched_on else SchedConfig(),
                     aot_cache=AotCacheConfig(enabled=True, dir=aot_dir)
                     if aot_dir else AotCacheConfig()),
        registry)
    node.boot(skip_self_test=True)
    return eng, node, mid, user


def test_disk_warm_buckets_count_as_warm_at_boot(tmp_path):
    """costsched's cross-life warm boost (docs/compile-cache.md): a
    bucket whose tag the boot scan found serialized packs as warm
    BEFORE anything compiled this life, and /debug/costmodel surfaces
    the disk-warm set."""
    from arbius_tpu.aotcache import env_signature
    from arbius_tpu.node.rpc import ControlRPC

    d = str(tmp_path / "shared")
    # a prior life (any fleet member) published this bucket
    _write_fixture(d, "sha256:prior", "a", b"P" * 64,
                   env=env_signature(), tag="faketag.b1.w768")
    eng, node, mid, user = _mini_world(tmp_path, aot_dir=d)
    assert node._disk_warm_tags == frozenset({"faketag.b1.w768"})
    (ev,) = node.obs.journal.events(kind="aot_cache_warm")
    assert ev["tags"] == ["faketag.b1.w768"]

    while node.tick():
        pass
    eng.submit_task(user, 0, user, bytes.fromhex(mid[2:]), 0,
                    json.dumps({"negative_prompt": "",
                                "prompt": "warm at boot"},
                               sort_keys=True).encode())
    for _ in range(16):
        if not node.tick() and eng.solutions:
            break
    assert eng.solutions, "task must solve"
    (packed,) = node._sched._last
    assert packed.warm, \
        "disk-warm bucket must pack warm before any compile this life"

    rpc = ControlRPC(node, port=0)
    code, payload = rpc.debug_view("/debug/costmodel")
    assert code == 200
    assert payload["aot_disk_warm"] == ["faketag.b1.w768"]
    json.dumps(payload, sort_keys=True)
    node.close()


def test_no_cache_no_disk_warm_and_cold_bucket_not_warm(tmp_path):
    eng, node, mid, user = _mini_world(tmp_path, aot_dir=None)
    assert node._disk_warm_tags == frozenset()
    while node.tick():
        pass
    eng.submit_task(user, 0, user, bytes.fromhex(mid[2:]), 0,
                    json.dumps({"negative_prompt": "", "prompt": "cold"},
                               sort_keys=True).encode())
    for _ in range(16):
        if not node.tick() and eng.solutions:
            break
    (packed,) = node._sched._last
    assert not packed.warm
    node.close()


# -- the 4-worker fleet over one shared cache dir ---------------------------

def test_fleet_shared_cache_dir_holds_invariants_zero_rejects(tmp_path):
    """Acceptance: a 4-worker fleet racing one clean event stream over
    ONE shared cache directory — real jitted probe programs — holds
    every applicable SIM1xx invariant (101-112) with zero
    `aot_cache_reject` events; the cache actually carried executables
    across workers (one compile+publish, three deserializes)."""
    from arbius_tpu.aotcache.store import scan
    from arbius_tpu.sim.fleet import FleetSimHarness
    from arbius_tpu.sim.invariants import check_all, classify_tasks
    from arbius_tpu.sim.scenario import FleetSpec, Scenario

    scn = Scenario(
        name="fleet-aot",
        description="4 workers, one shared AOT cache dir, clean faults",
        tasks=8, burst=4, strict=True, fleet=FleetSpec(workers=4))
    workdir = tmp_path / "fleetaot"
    workdir.mkdir()
    aot_dir = str(tmp_path / "shared-aot")
    harness = FleetSimHarness(scn, 1, str(workdir), aot_dir=aot_dir)
    result = harness.run()
    findings = check_all(result)
    assert not findings, (
        "invariant violations over the shared cache:\n  "
        + "\n  ".join(f.text() for f in findings))
    assert result.quiescent
    assert set(classify_tasks(result).values()) == {"claimed"}
    rejects = [e for e in result.journal_events
               if e.get("kind") == "aot_cache_reject"]
    assert rejects == [], "clean fleet run must have zero cache rejects"
    # workers tick sequentially in-process, so the split is exact: the
    # first dispatcher compiled + published, every later worker's first
    # dispatch deserialized the shared entry
    per_worker = [_counters(w.obs) for w in harness.workers]
    assert sum(c["writes"] for c in per_worker) == 1
    assert sum(c["compiles"] for c in per_worker) == 1
    loaders = [c for c in per_worker if c["loads"]]
    assert len(loaders) == 3, \
        "three of four workers must have deserialized, not compiled"
    assert sum(c["rejects"] for c in per_worker) == 0
    assert len(scan(aot_dir)) == 1, "one bucket ⇒ one shared entry"


# -- config + CLI -----------------------------------------------------------

def test_aot_cache_config_loads_and_validates():
    from arbius_tpu.node.config import ConfigError, load_config

    cfg = load_config({"aot_cache": {"enabled": True, "dir": "/x/y",
                                     "max_bytes": 123}})
    assert cfg.aot_cache.enabled and cfg.aot_cache.dir == "/x/y"
    assert cfg.aot_cache.max_bytes == 123
    assert not load_config({}).aot_cache.enabled  # default: off
    with pytest.raises(ConfigError, match="aot_cache.dir"):
        load_config({"aot_cache": {"enabled": True, "dir": ""}})
    with pytest.raises(ConfigError, match="aot_cache.max_bytes"):
        load_config({"aot_cache": {"max_bytes": -1}})
    with pytest.raises(ConfigError, match="aot_cache"):
        load_config({"aot_cache": {"unknown_key": 1}})


def _build_cli_fixture(cache_dir: str) -> None:
    """The deterministic fixture cache the CLI goldens pin: one valid
    entry, one whose header does not re-derive its key (AOT501), one
    truncated (AOT502). Everything fixed — synthetic env, fixed
    payloads — so reports are byte-stable on any host."""
    _write_fixture(cache_dir, "sha256:good", "argsA", b"GOOD" * 64,
                   tag="sd15.1.64.64.2.DDIM")
    _write_fixture(cache_dir, "sha256:renamed", "argsB", b"BADK" * 64,
                   tag="renamed.tag",
                   key="ab" * 32)  # filename ≠ derived key
    _, path = _write_fixture(cache_dir, "sha256:trunc", "argsC",
                             b"TRNC" * 64, tag="trunc.tag")
    with open(path, "r+b") as f:
        f.truncate(70)


def _run_cli(args):
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "aotcache.py")]
        + args, capture_output=True, text=True, timeout=120)
    return r.returncode, r.stdout


GOLDEN_DIR = os.path.join(REPO, "tests", "fixtures", "aotcache")


@pytest.mark.parametrize("mode,golden,rc_want", [
    (["--verify", "--json"], "verify.golden.json", 1),
    (["--list", "--json"], "list.golden.json", 0),
])
def test_cli_reports_pinned_byte_deterministic(tmp_path, mode, golden,
                                               rc_want):
    """`tools/aotcache.py` on the fixture cache: exit codes per the
    shared lint contract and byte-identical reports (tier-1 golden)."""
    d = str(tmp_path / "fixture")
    _build_cli_fixture(d)
    rc, out = _run_cli(["--dir", d] + mode)
    assert rc == rc_want
    with open(os.path.join(GOLDEN_DIR, golden)) as f:
        assert out == f.read()


def test_cli_verify_clean_and_usage_errors(tmp_path):
    d = str(tmp_path / "ok")
    _write_fixture(d, "sha256:good", "a", b"OK" * 32, tag="t")
    rc, out = _run_cli(["--dir", d, "--verify"])
    assert rc == 0 and "verified clean" in out
    rc, _ = _run_cli(["--dir", d])                      # no mode
    assert rc == 2
    rc, _ = _run_cli(["--dir", d, "--list", "--stats"])  # two modes
    assert rc == 2
    rc, _ = _run_cli(["--dir", d, "--gc"])               # gc w/o budget
    assert rc == 2


def test_cli_gc_applies_lru(tmp_path):
    d = str(tmp_path / "gc")
    _, p1 = _write_fixture(d, "sha256:old", "a", b"O" * 512, tag="old")
    os.utime(p1, (1, 1))
    _write_fixture(d, "sha256:new", "a", b"N" * 512, tag="new")
    rc, out = _run_cli(["--dir", d, "--gc", "--max-bytes", "1000",
                        "--json"])
    assert rc == 0
    doc = json.loads(out)
    assert len(doc["evicted"]) == 1 and doc["remaining_entries"] == 1
    from arbius_tpu.aotcache import read_header
    from arbius_tpu.aotcache.store import scan

    (entry,) = scan(d)
    assert read_header(entry[1])["tag"] == "new"
