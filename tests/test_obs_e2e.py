"""End-to-end obs tests: a full task lifecycle traced through
`MinerNode.tick()` on the fake chain, the ControlRPC observability
endpoints (/metrics Prometheus parse, /debug/trace span tree,
/debug/journal), the 500-on-view-failure contract, obs_dump rendering,
and the bounded-overhead acceptance check."""
from __future__ import annotations

import json
import os
import sys
import time
import urllib.error
import urllib.request

import pytest

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "tools"))

from arbius_tpu.chain import WAD
from arbius_tpu.node.rpc import ControlRPC

from test_node import build_world, drain, submit
from test_obs import assert_valid_prometheus


def _solved_world():
    eng, tok, chain, node, mid = build_world()
    tid = submit(eng, mid, fee=10 * WAD)
    drain(node)
    assert node.metrics.solutions_submitted == 1
    return eng, tok, chain, node, mid, tid


def _names(spans):
    out = []
    for sp in spans:
        out.append(sp["name"])
        out.extend(_names(sp.get("children") or []))
    return out


def test_full_lifecycle_trace_through_tick():
    eng, tok, chain, node, mid, tid = _solved_world()
    eng.advance_time(2000 + 121)
    drain(node)
    assert node.metrics.solutions_claimed == 1

    roots = node.obs.task_trace(tid)
    names = _names(roots)
    # the ISSUE's lifecycle: event → hydrate → infer/batch → encode →
    # CID → pin → commit → reveal → claim
    for expected in ("task.event", "job.task", "task.hydrate",
                     "solve.batch", "solve.infer", "solve.cid",
                     "solve.task", "solve.pin", "solve.commit",
                     "chain.signal_commitment", "solve.reveal",
                     "chain.submit_solution", "job.claim",
                     "chain.claim_solution"):
        assert expected in names, f"{expected} missing from {names}"
    # nesting: solve.infer and solve.task live under solve.batch
    batch = next(sp for r in roots for sp in [r] + r["children"]
                 if sp["name"] == "solve.batch")
    batch_children = {c["name"] for c in batch["children"]}
    assert {"solve.infer", "solve.cid", "solve.task"} <= batch_children
    assert tid in batch["taskids"]
    # chain-time stamps rode along
    assert all("chain_start" in r for r in roots)
    # per-task latency landed in the tagged histogram window
    assert node.metrics.solve_latency[0][0] == tid
    assert node.metrics.solve_latency[0][1] >= 0
    # stage histogram fed by the bucket dispatch
    assert len(node.metrics.stage_seconds["infer"]) == 1
    assert len(node.metrics.stage_seconds["commit"]) == 1


def test_failed_job_recorded_in_journal_and_counter():
    eng, tok, chain, node, mid = build_world()
    node.db.queue_job("task", {"taskid": "0x" + "77" * 32})  # not on chain
    drain(node)
    fails = node.obs.journal.events(kind="job_failed")
    assert len(fails) == 1
    assert fails[0]["method"] == "task" and "not on chain" in fails[0]["error"]
    assert node.obs.registry.counter(
        "arbius_jobs_failed_total", labelnames=("method",)).value(
        method="task") == 1
    # the failing span itself carries error status
    spans = [e for e in node.obs.journal.events(kind="span")
             if e["name"] == "job.task"]
    assert spans and spans[-1]["status"] == "error"


@pytest.fixture()
def rpc_world():
    eng, tok, chain, node, mid, tid = _solved_world()
    rpc = ControlRPC(node, port=0)
    rpc.start()
    yield eng, node, rpc, tid
    rpc.stop()


def _get(port, path):
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}{path}", timeout=5) as r:
        ctype = r.headers.get("Content-Type", "")
        body = r.read().decode()
    return ctype, body


def test_metrics_endpoint_is_valid_prometheus(rpc_world):
    eng, node, rpc, tid = rpc_world
    ctype, text = _get(rpc.port, "/metrics")
    assert ctype.startswith("text/plain")
    samples = assert_valid_prometheus(text)
    assert samples["arbius_solutions_submitted_total"] == 1
    assert samples["arbius_tasks_seen_total"] == 1
    assert samples["arbius_solve_latency_chain_seconds_count"] == 1
    assert 'arbius_stage_seconds_count{stage="infer"}' in samples
    assert 'arbius_span_seconds_count{name="solve.infer"}' in samples
    assert "arbius_queue_depth" in samples
    # JSON view is served off the same registry and keeps its keys
    _, js = _get(rpc.port, "/api/metrics")
    m = json.loads(js)
    assert m["solutions_submitted"] == 1
    assert m["solve_latency_p50"] is not None
    assert m["stage_infer_p50_s"] is not None


def test_debug_trace_endpoint_returns_span_tree(rpc_world):
    eng, node, rpc, tid = rpc_world
    _, body = _get(rpc.port, f"/debug/trace?taskid={tid}")
    payload = json.loads(body)
    assert payload["taskid"] == tid
    names = _names(payload["spans"])
    assert "solve.batch" in names and "solve.reveal" in names
    # missing taskid → 400, not a dead thread
    with pytest.raises(urllib.error.HTTPError) as ei:
        _get(rpc.port, "/debug/trace")
    assert ei.value.code == 400


def test_debug_journal_endpoint(rpc_world):
    eng, node, rpc, tid = rpc_world
    _, body = _get(rpc.port, "/debug/journal?limit=5&kind=span")
    payload = json.loads(body)
    assert payload["capacity"] == node.config.obs_journal_capacity
    assert 0 < len(payload["events"]) <= 5
    assert all(e["kind"] == "span" for e in payload["events"])
    # an operator typo is a 400 (client error), not a counted 500
    with pytest.raises(urllib.error.HTTPError) as ei:
        _get(rpc.port, "/debug/journal?limit=abc")
    assert ei.value.code == 400
    assert node.obs.registry.counter("arbius_rpc_errors_total").value() == 0


def test_failing_view_returns_500_and_counts(rpc_world, monkeypatch):
    eng, node, rpc, tid = rpc_world
    monkeypatch.setattr(
        rpc, "metrics", lambda: (_ for _ in ()).throw(RuntimeError("view!")))
    with pytest.raises(urllib.error.HTTPError) as ei:
        _get(rpc.port, "/api/metrics")
    assert ei.value.code == 500
    assert "view!" in json.loads(ei.value.read().decode())["error"]
    assert node.obs.registry.counter("arbius_rpc_errors_total").value() == 1
    # the server thread survived: the next request still answers
    _, body = _get(rpc.port, "/api/tasks")
    assert json.loads(body)[0]["taskid"] == tid


def test_obs_dump_renderers(rpc_world):
    from obs_dump import fetch_json, render_journal, render_metrics, \
        render_trace

    eng, node, rpc, tid = rpc_world
    base = f"http://127.0.0.1:{rpc.port}"
    out = render_metrics(fetch_json(f"{base}/api/metrics"))
    assert "solutions_submitted" in out
    body = fetch_json(f"{base}/debug/trace?taskid={tid}")
    tree = render_trace(body["spans"])
    assert "job.task" in tree and "solve.infer" in tree and "ms" in tree
    # children are indented under their parents
    batch_line = next(l for l in tree.splitlines()
                      if l.strip().startswith("solve.batch"))
    infer_line = next(l for l in tree.splitlines()
                      if l.strip().startswith("solve.infer"))
    assert len(infer_line) - len(infer_line.lstrip()) > \
        len(batch_line) - len(batch_line.lstrip())
    jr = render_journal(
        fetch_json(f"{base}/debug/journal?limit=10")["events"])
    assert "span" in jr


def test_journal_capacity_config_bounds_node_journal():
    eng, tok, chain, node, mid = build_world(obs_journal_capacity=8)
    for i in range(4):
        submit(eng, mid, prompt=f"cat {i}", fee=10 * WAD)
    drain(node)
    assert len(node.obs.journal) == 8
    assert node.obs.journal.dropped > 0


# -- acceptance: bounded instrumentation overhead --------------------------

def _burst_seconds(obs_enabled: bool, n_tasks: int = 8) -> float:
    eng, tok, chain, node, mid = build_world(obs_enabled=obs_enabled)
    for i in range(n_tasks):
        submit(eng, mid, prompt=f"task {i}", fee=10 * WAD)
    t0 = time.perf_counter()
    drain(node, n=50)
    dt = time.perf_counter() - t0
    assert node.metrics.solutions_submitted == n_tasks
    return dt


@pytest.mark.slow
def test_obs_overhead_bounded():
    """test_smoke_burst-style run with obs on vs off: the tick loop may
    not slow down more than 5% (plus a small absolute epsilon for timer
    noise). Interleaved best-of-5 so scheduler jitter cancels."""
    on, off = [], []
    _burst_seconds(True)  # warm caches (sqlite, templates, imports)
    for _ in range(5):
        off.append(_burst_seconds(False))
        on.append(_burst_seconds(True))
    assert min(on) <= min(off) * 1.05 + 0.010, (min(on), min(off))
