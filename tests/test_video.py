"""Video family tests: ring attention exactness, UNet3D inflation property,
pipeline determinism, and sp=1 vs sp=2 equivalence on the CPU mesh — the
sequence-parallel path SURVEY.md §2.6 requires as first-class.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.experimental.shard_map import shard_map
from jax.sharding import PartitionSpec as P

from arbius_tpu.models.video import (
    Text2VideoConfig,
    Text2VideoPipeline,
    UNet3DCondition,
    UNet3DConfig,
)
from arbius_tpu.models.sd15 import ByteTokenizer
from arbius_tpu.ops import (
    ring_attention,
    sp_attention_reference,
    ulysses_attention,
)
from arbius_tpu.parallel import MeshSpec, build_mesh

pytestmark = [pytest.mark.slow, pytest.mark.model]


def tok():
    return ByteTokenizer(max_length=16, bos_id=257, eos_id=258)


# -- ring attention --------------------------------------------------------

def test_ring_attention_matches_reference():
    """Exactness oracle: ring accumulation over 4 shards ≡ full softmax."""
    mesh = build_mesh(MeshSpec(sp=4), devices=jax.devices()[:4])
    B, H, S, D = 2, 3, 16, 8
    k1, k2, k3 = jax.random.split(jax.random.PRNGKey(0), 3)
    q = jax.random.normal(k1, (B, H, S, D), jnp.float32)
    k = jax.random.normal(k2, (B, H, S, D), jnp.float32)
    v = jax.random.normal(k3, (B, H, S, D), jnp.float32)

    ring = jax.jit(shard_map(
        lambda q, k, v: ring_attention(q, k, v, axis_name="sp"),
        mesh=mesh, in_specs=P(None, None, "sp", None),
        out_specs=P(None, None, "sp", None), check_rep=False))
    got = np.asarray(ring(q, k, v))
    want = np.asarray(sp_attention_reference(q, k, v))
    np.testing.assert_allclose(got, want, rtol=2e-5, atol=2e-5)


def test_ring_attention_extreme_logits_stable():
    """Online-softmax must survive large score magnitudes (f32 stats)."""
    mesh = build_mesh(MeshSpec(sp=2), devices=jax.devices()[:2])
    B, H, S, D = 1, 1, 8, 4
    q = jnp.full((B, H, S, D), 30.0, jnp.float32)
    k = jnp.full((B, H, S, D), 30.0, jnp.float32)
    v = jax.random.normal(jax.random.PRNGKey(1), (B, H, S, D), jnp.float32)
    ring = jax.jit(shard_map(
        lambda q, k, v: ring_attention(q, k, v, axis_name="sp"),
        mesh=mesh, in_specs=P(None, None, "sp", None),
        out_specs=P(None, None, "sp", None), check_rep=False))
    out = np.asarray(ring(q, k, v))
    assert np.isfinite(out).all()
    np.testing.assert_allclose(out, np.asarray(sp_attention_reference(q, k, v)),
                               rtol=1e-5, atol=1e-5)


# -- unet3d ----------------------------------------------------------------

def test_unet3d_shapes_and_inflation():
    """Zero-init temporal branches ⇒ at init, frames evolve independently:
    a batch of T identical frames must produce T identical outputs."""
    cfg = UNet3DConfig.tiny()
    model = UNet3DCondition(cfg)
    B, T, H, W = 1, 4, 16, 16
    frame = jax.random.normal(jax.random.PRNGKey(1), (B, 1, H, W, 4))
    x = jnp.tile(frame, (1, T, 1, 1, 1))
    ctx = jax.random.normal(jax.random.PRNGKey(2), (B, 8, cfg.context_dim))
    params = model.init(jax.random.PRNGKey(0), x, jnp.zeros((B,)), ctx)["params"]
    out = model.apply({"params": params}, x, jnp.ones((B,)), ctx)
    assert out.shape == (B, T, H, W, 4)
    for f in range(1, T):
        np.testing.assert_allclose(np.asarray(out[:, 0]),
                                   np.asarray(out[:, f]), rtol=1e-5, atol=1e-5)


# -- pipeline --------------------------------------------------------------

def test_pipeline_generate_deterministic():
    pipe = Text2VideoPipeline(Text2VideoConfig.tiny(), tokenizer=tok())
    params = pipe.init_params(seed=0)
    kw = dict(num_frames=4, width=64, height=64, num_inference_steps=2,
              scheduler="DDIM")
    a = pipe.generate(params, ["a rocket"], None, [7], **kw)
    b = pipe.generate(params, ["a rocket"], None, [7], **kw)
    assert a.shape == (1, 4, 64, 64, 3) and a.dtype == np.uint8
    np.testing.assert_array_equal(a, b)
    c = pipe.generate(params, ["a rocket"], None, [8], **kw)
    assert not np.array_equal(a, c)


def test_pipeline_sp2_matches_sp1():
    """The sp layout must not change WHAT is computed: sp=2 over 2 devices
    vs single-device, same params/inputs → same video up to reduction-
    order rounding (and bit-identical with itself across runs)."""
    kw = dict(num_frames=4, width=64, height=64, num_inference_steps=2,
              scheduler="DDIM")
    ref_pipe = Text2VideoPipeline(Text2VideoConfig.tiny(), tokenizer=tok())
    params = ref_pipe.init_params(seed=0)
    ref = ref_pipe.generate(params, ["orbit"], None, [3], **kw)

    mesh = build_mesh(MeshSpec(sp=2), devices=jax.devices()[:2])
    sp_pipe = Text2VideoPipeline(Text2VideoConfig.tiny(sp_axis="sp"),
                                 tokenizer=tok(), mesh=mesh)
    a = sp_pipe.generate(params, ["orbit"], None, [3], **kw)
    b = sp_pipe.generate(params, ["orbit"], None, [3], **kw)
    np.testing.assert_array_equal(a, b)  # sp path bit-deterministic
    # numerically the same video (uint8 quantization absorbs rounding)
    diff = np.abs(a.astype(int) - ref.astype(int))
    assert diff.max() <= 1, diff.max()
    assert (diff > 0).mean() < 0.02


def test_pipeline_sp_strategy_ulysses_matches_sp1():
    """sp_strategy="ulysses" through the PRODUCTION pipeline call-site:
    all-to-all SP must produce the same video as the unsharded reference
    (tiny topology: 2 heads per level, sp=2 divides them)."""
    kw = dict(num_frames=4, width=64, height=64, num_inference_steps=2,
              scheduler="DDIM")
    ref_pipe = Text2VideoPipeline(Text2VideoConfig.tiny(), tokenizer=tok())
    params = ref_pipe.init_params(seed=0)
    ref = ref_pipe.generate(params, ["orbit"], None, [3], **kw)

    mesh = build_mesh(MeshSpec(sp=2), devices=jax.devices()[:2])
    uly_pipe = Text2VideoPipeline(
        Text2VideoConfig.tiny(sp_axis="sp", sp_strategy="ulysses"),
        tokenizer=tok(), mesh=mesh)
    a = uly_pipe.generate(params, ["orbit"], None, [3], **kw)
    b = uly_pipe.generate(params, ["orbit"], None, [3], **kw)
    np.testing.assert_array_equal(a, b)  # bit-deterministic
    diff = np.abs(a.astype(int) - ref.astype(int))
    assert diff.max() <= 1, diff.max()
    assert (diff > 0).mean() < 0.02


def test_factory_builds_sp_strategy_from_model_config():
    """The node's config → factory path selects the strategy: a video
    ModelConfig(sp_strategy=...) reaches the unet on an sp>1 mesh."""
    from arbius_tpu.node.config import ConfigError, MiningConfig, ModelConfig
    from arbius_tpu.node.factory import build_registry

    mesh = build_mesh(MeshSpec(sp=2), devices=jax.devices()[:2])
    mc = ModelConfig(id="0x" + "22" * 32, template="zeroscopev2xl",
                     tiny=True, sp_strategy="ulysses")
    reg = build_registry(MiningConfig(models=(mc,)), mesh=mesh)
    runner = reg.get(mc.id).runner
    ucfg = runner.pipeline.config.unet
    assert ucfg.sp_axis == "sp" and ucfg.sp_strategy == "ulysses"

    with pytest.raises(ConfigError, match="sp_strategy"):
        ModelConfig(id="0x" + "22" * 32, template="zeroscopev2xl",
                    sp_strategy="nope")


def test_pipeline_dp_and_sp_mesh():
    mesh = build_mesh(MeshSpec(dp=2, sp=2), devices=jax.devices()[:4])
    pipe = Text2VideoPipeline(Text2VideoConfig.tiny(sp_axis="sp"),
                              tokenizer=tok(), mesh=mesh)
    params = pipe.init_params(seed=0)
    out = pipe.generate(params, ["a", "b"], None, [1, 2], num_frames=4,
                        width=64, height=64, num_inference_steps=2)
    assert out.shape == (2, 4, 64, 64, 3)


def test_pipeline_frame_divisibility_check():
    mesh = build_mesh(MeshSpec(sp=2), devices=jax.devices()[:2])
    pipe = Text2VideoPipeline(Text2VideoConfig.tiny(sp_axis="sp"),
                              tokenizer=tok(), mesh=mesh)
    params = pipe.init_params(seed=0)
    with pytest.raises(ValueError, match="divisible by sp"):
        pipe.generate(params, ["x"], None, [1], num_frames=3, width=64,
                      height=64, num_inference_steps=2)


def test_pipeline_mismatched_config_rejected():
    mesh = build_mesh(MeshSpec(sp=2), devices=jax.devices()[:2])
    with pytest.raises(ValueError, match="sharding-aware"):
        Text2VideoPipeline(Text2VideoConfig.tiny(), tokenizer=tok(),
                          mesh=mesh)


def test_video_to_mp4_path():
    """Frames → deterministic MP4 bytes (the artifact the CID binds)."""
    from arbius_tpu.codecs import encode_mp4

    pipe = Text2VideoPipeline(Text2VideoConfig.tiny(), tokenizer=tok())
    params = pipe.init_params(seed=0)
    frames = pipe.generate(params, ["clip"], None, [5], num_frames=2,
                           width=64, height=64, num_inference_steps=2)
    m1 = encode_mp4(frames[0], fps=8)
    m2 = encode_mp4(frames[0].copy(), fps=8)
    assert m1 == m2 and m1[4:8] == b"ftyp"


def test_ulysses_attention_matches_reference():
    """All-to-all SP (DeepSpeed-Ulysses form) ≡ full softmax, exactly —
    the second first-class long-context strategy beside ring."""
    mesh = build_mesh(MeshSpec(sp=4), devices=jax.devices()[:4])
    B, H, S, D = 2, 4, 16, 8   # H divisible by sp
    k1, k2, k3 = jax.random.split(jax.random.PRNGKey(5), 3)
    q = jax.random.normal(k1, (B, H, S, D), jnp.float32)
    k = jax.random.normal(k2, (B, H, S, D), jnp.float32)
    v = jax.random.normal(k3, (B, H, S, D), jnp.float32)
    uly = jax.jit(shard_map(
        lambda q, k, v: ulysses_attention(q, k, v, axis_name="sp"),
        mesh=mesh, in_specs=P(None, None, "sp", None),
        out_specs=P(None, None, "sp", None), check_rep=False))
    got = np.asarray(uly(q, k, v))
    want = np.asarray(sp_attention_reference(q, k, v))
    np.testing.assert_allclose(got, want, rtol=2e-5, atol=2e-5)
    # ulysses and ring agree with each other too
    ring = jax.jit(shard_map(
        lambda q, k, v: ring_attention(q, k, v, axis_name="sp"),
        mesh=mesh, in_specs=P(None, None, "sp", None),
        out_specs=P(None, None, "sp", None), check_rep=False))
    np.testing.assert_allclose(got, np.asarray(ring(q, k, v)),
                               rtol=2e-5, atol=2e-5)


def test_ulysses_rejects_indivisible_heads():
    mesh = build_mesh(MeshSpec(sp=4), devices=jax.devices()[:4])
    q = jnp.zeros((1, 3, 16, 4))  # 3 heads, sp=4
    f = shard_map(
        lambda q: ulysses_attention(q, q, q, axis_name="sp"),
        mesh=mesh, in_specs=P(None, None, "sp", None),
        out_specs=P(None, None, "sp", None), check_rep=False)
    with pytest.raises(ValueError, match="divisible"):
        f(q)


def test_factory_rejects_ulysses_indivisible_heads_at_boot():
    """The full zeroscope topology has a 5-head temporal level
    (320/64); ulysses on sp=2 must be rejected when the registry is
    BUILT, not at first-task trace time."""
    from arbius_tpu.node.config import ConfigError, MiningConfig, ModelConfig
    from arbius_tpu.node.factory import build_registry

    mesh = build_mesh(MeshSpec(sp=2), devices=jax.devices()[:2])
    mc = ModelConfig(id="0x" + "23" * 32, template="zeroscopev2xl",
                     tiny=False, sp_strategy="ulysses")
    with pytest.raises(ConfigError, match="head count"):
        build_registry(MiningConfig(models=(mc,)), mesh=mesh)
