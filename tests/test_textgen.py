"""textgen tier-1 suite (docs/text-serving.md): the jitted KV-cache
decode loop's determinism contract (same inputs → same tokens; the
decode EDGE is prefix-stable, the prompt edge is consensus config),
the sequence-aware bucket key (9-tuples extend, 6/7-tuple legacy keys
parse byte for byte), ragged-bucket chunk padding, the validated
`textgen` config block, the costmodel render cap, the decode_stall
healthwatch rule, the text-stream simnet scenario under SIM101-113,
and the e2e CID matrix through a real MinerNode (pipeline on/off ×
AOT off/cold/warm × mesh-off/dp2)."""
from __future__ import annotations

import json
import os
import sys

import jax
import numpy as np
import pytest

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "tools"))

from arbius_tpu.models.textgen import (
    TextGenConfig,
    TextGenPipeline,
    tokens_to_bytes,
)
from arbius_tpu.node.config import ConfigError, TextgenConfig, load_config
from arbius_tpu.node.costmodel import bucket_str
from arbius_tpu.node.solver import (
    TextGenRunner,
    bucket_key,
    bucket_mode,
    chunk_items,
    count_decode_stall,
)

REPO = os.path.join(os.path.dirname(__file__), "..")

# tiny trace-speed bucket edges: 8+4 positions out of tiny()'s 96
P_EDGES = (8, 16)
T_EDGES = (4, 8)


@pytest.fixture(scope="module")
def pipe():
    return TextGenPipeline(TextGenConfig.tiny(), prompt_buckets=P_EDGES,
                           decode_buckets=T_EDGES, top_k=4)


@pytest.fixture(scope="module")
def params(pipe):
    return pipe.init_params(seed=0)


# -- the decode loop's determinism contract ---------------------------------

def test_generate_is_deterministic_per_sampler(pipe, params):
    for sampler in ("greedy", "top_k"):
        a = pipe.generate(params, ["hi"], [1234], prompt_bucket=8,
                          decode_bucket=4, sampler=sampler)
        b = pipe.generate(params, ["hi"], [1234], prompt_bucket=8,
                          decode_bucket=4, sampler=sampler)
        assert np.array_equal(a, b), f"{sampler} tokens drifted"
        assert a.shape == (1, 4) and a.dtype == np.int32


def test_decode_edge_is_prefix_stable(pipe, params):
    """The load-bearing claim of docs/text-serving.md: the decode
    bucket edge is NOT bytes-affecting. A longer decode bucket's first
    T tokens are bit-identical to the shorter bucket's output, for both
    samplers — so host-side truncation to the requested budget is sound
    and decode edges are free per-node config."""
    for sampler in ("greedy", "top_k"):
        short = pipe.generate(params, ["prefix check"], [7],
                              prompt_bucket=16, decode_bucket=4,
                              sampler=sampler)
        long = pipe.generate(params, ["prefix check"], [7],
                             prompt_bucket=16, decode_bucket=8,
                             sampler=sampler)
        assert np.array_equal(short[0], long[0, :4]), \
            f"{sampler}: decode edge changed the shared prefix"


def test_top_k_threads_the_task_seed(pipe, params):
    """Two task seeds must be able to sample different tokens (the
    seed is an INPUT to one compiled program, docs/text-serving.md);
    greedy ignores the seed entirely."""
    a = pipe.generate(params, ["seed check"], [1], prompt_bucket=16,
                      decode_bucket=8, sampler="top_k")
    b = pipe.generate(params, ["seed check"], [2], prompt_bucket=16,
                      decode_bucket=8, sampler="top_k")
    assert not np.array_equal(a, b), \
        "top_k sampled identically under different seeds"
    g1 = pipe.generate(params, ["seed check"], [1], prompt_bucket=16,
                       decode_bucket=8, sampler="greedy")
    g2 = pipe.generate(params, ["seed check"], [2], prompt_bucket=16,
                       decode_bucket=8, sampler="greedy")
    assert np.array_equal(g1, g2), "greedy must be seed-free"


def test_bucket_policy_smallest_edge_that_fits(pipe):
    # "hi" needs 2+2=4 bytes+specials → first edge 8
    assert pipe.prompt_bucket_for("hi") == 8
    # 7 bytes + 2 → 9 > 8 → next edge
    assert pipe.prompt_bucket_for("seven77") == 16
    # over-long prompts clamp to the top edge (tokenizer truncation)
    assert pipe.prompt_bucket_for("x" * 100) == 16
    assert pipe.decode_bucket_for(1) == 4
    assert pipe.decode_bucket_for(5) == 8
    assert pipe.decode_bucket_for(999) == 8  # clamped; config caps it


def test_tokens_to_bytes_total_over_model_vocab():
    # stops at the first eos, drops non-byte ids, honors the limit
    ids = [104, 105, 300, 33, 258, 104]
    assert tokens_to_bytes(ids, 6) == b"hi!"
    assert tokens_to_bytes(ids, 2) == b"hi"
    assert tokens_to_bytes([258, 104], 2) == b""
    assert tokens_to_bytes([511, 257], 2) == b""  # nothing representable


def test_trace_specs_cover_prefill_decode_and_generate():
    from arbius_tpu.models.trace_specs import all_trace_specs

    specs = [s for s in all_trace_specs() if s.model == "textgen"]
    entries = sorted({s.entry for s in specs})
    assert entries == ["decode", "generate", "prefill"]
    assert len(specs) == 6
    # both samplers goldened as separate decode classes
    assert {s.bucket for s in specs if s.entry == "decode"} == \
        {"b1.p8.t4.greedy", "b1.p8.t4.top_k"}


# -- bucket key: 9-tuple extension, legacy parse (satellite) ----------------

def test_bucket_key_legacy_shapes_unchanged():
    img = {"width": 512, "height": 512, "num_inference_steps": 20,
           "scheduler": "DDIM"}
    key = bucket_key("0xabc", img)
    assert key == ("0xabc", 512, 512, 20, "DDIM", None, "bf16")
    assert len(key) == 7
    assert bucket_mode(key) == "bf16"
    # pre-quant 6-tuples (persisted rows) still read as bf16
    assert bucket_mode(key[:6]) == "bf16"
    assert bucket_str(key) == "512x512.s20.DDIM.f-"
    assert bucket_str(key[:6]) == "512x512.s20.DDIM.f-"


def test_bucket_key_text_9_tuple_and_sampler_slot():
    hyd = {"prompt": "hi", "sampler": "top_k", "max_new_tokens": 8,
           "_prompt_bucket": 32, "_decode_bucket": 16}
    key = bucket_key("0xdef", hyd, mode="int8")
    assert key == ("0xdef", None, None, None, "top_k", None, "int8",
                   32, 16)
    assert bucket_mode(key) == "int8"
    assert bucket_str(key) == "-x-.s-.top_k.f-.p32.t16"
    # without the injected fields the SAME hydrated input stays 7-wide
    bare = {k: v for k, v in hyd.items() if not k.startswith("_")}
    assert len(bucket_key("0xdef", bare)) == 7


def test_runner_prepare_hydrated_stamps_buckets(pipe, params):
    r = TextGenRunner(pipe, params)
    h = r.prepare_hydrated({"prompt": "hi", "max_new_tokens": 5})
    assert (h["_prompt_bucket"], h["_decode_bucket"]) == (8, 8)
    # pure function of (input, config): idempotent and input untouched
    assert r.prepare_hydrated(h) == h
    assert "_prompt_bucket" not in {"prompt": "hi"}


def test_chunk_items_ragged_bucket_padding():
    items = [({"i": n}, n) for n in range(5)]
    chunks = chunk_items(items, 2)
    assert [(len(c), real) for c, real in chunks] == [(2, 2), (2, 2),
                                                      (2, 1)]
    # the ragged tail pads by REPEATING its last real item, never by
    # inventing one — the padded twin's bytes are discarded by n_real
    tail, real = chunks[-1]
    assert tail == [({"i": 4}, 4), ({"i": 4}, 4)] and real == 1
    # batch larger than the bucket: one chunk, fully padded
    (only,) = chunk_items(items[:1], 4)
    assert only == ([({"i": 0}, 0)] * 4, 1)


def test_cold_sequence_buckets_price_token_linearly():
    """node/sched.py static_seq (docs/scheduler.md): a cold 9-tuple
    prices at the static estimate scaled by its token count — ordering
    only, but a 96-token bucket must not price like a 20-token one."""
    from arbius_tpu.node.sched import CostSched

    class _Model:
        def predict(self, *a):
            return None

    class _Node:
        costmodel = _Model()
        solve_layout = "single"

        def _static_solve_seconds(self):
            return 10.0

    sched = CostSched.__new__(CostSched)
    sched.node = _Node()
    seq = ("m", None, None, None, "greedy", None, "bf16", 32, 16)
    assert sched._predict(seq, 1) == (10.0 * 48 / 64, "static_seq")
    legacy = ("m", 512, 512, 20, "DDIM", None, "bf16")
    assert sched._predict(legacy, 1) == (10.0, "static")


# -- config block (satellite) -----------------------------------------------

def test_textgen_config_validation_messages():
    with pytest.raises(ConfigError, match="ascending"):
        TextgenConfig(prompt_buckets=(32, 16))
    with pytest.raises(ConfigError, match="non-empty"):
        TextgenConfig(decode_buckets=())
    with pytest.raises(ConfigError, match=">= 3"):
        TextgenConfig(prompt_buckets=(2, 32))
    with pytest.raises(ConfigError, match="unmineable"):
        TextgenConfig(decode_buckets=(4, 8), max_new_tokens=9)
    with pytest.raises(ConfigError, match="top_k"):
        TextgenConfig(top_k=0)
    with pytest.raises(ConfigError, match="max_new_tokens"):
        TextgenConfig(max_new_tokens=0)


def test_example_config_carries_the_textgen_block():
    with open(os.path.join(REPO, "MiningConfig.example.json")) as f:
        cfg = load_config(f.read())
    assert cfg.textgen.prompt_buckets == (32, 64)
    assert cfg.textgen.decode_buckets == (16, 32)
    assert cfg.textgen.max_new_tokens == 32
    assert cfg.textgen.top_k == 8
    assert any(m.template == "textgen" for m in cfg.models)


def test_unknown_textgen_key_is_one_sentence():
    base = {"db_path": "x", "textgen": {"bogus": 1}}
    with pytest.raises(ConfigError, match="textgen"):
        load_config(json.dumps(base))


# -- costmodel render cap (satellite) ---------------------------------------

def test_render_rows_caps_with_explicit_omission_line():
    from costmodel import RENDER_CAP, render_rows

    def row(i):
        return {"model": f"m{i:03d}", "bucket": f"b{i}", "layout":
                "single", "mode": "bf16", "chip_seconds": 1.0,
                "samples": 2, "updated": 3}

    out = render_rows([row(i) for i in range(RENDER_CAP + 6)])
    lines = out.splitlines()
    assert lines[-1] == "(6 more buckets)"
    assert len(lines) == 1 + RENDER_CAP + 1  # header + cap + trailer
    # at or under the cap: no trailer, historic table byte for byte
    under = render_rows([row(i) for i in range(RENDER_CAP)])
    assert "more buckets" not in under
    assert len(under.splitlines()) == 1 + RENDER_CAP


# -- decode_stall healthwatch rule ------------------------------------------

class _FakeChain:
    now = 0

    def get_blocktime(self):
        return self.now


class _FakeDB:
    due: list = []

    def get_jobs(self, now, limit=None):
        return self.due[:limit]


class _FakeNode:
    def __init__(self, obs):
        self.obs = obs
        self.chain = _FakeChain()
        self.db = _FakeDB()
        self.task_feed = None


def test_decode_stall_rule_fires_on_counter_delta():
    from arbius_tpu.node.config import AlertsConfig
    from arbius_tpu.obs import Obs, use_obs
    from arbius_tpu.obs.healthwatch import RULE_NAMES, HealthWatch

    assert "decode_stall" in RULE_NAMES
    obs = Obs()
    hw = HealthWatch(obs, AlertsConfig(enabled=True))
    node = _FakeNode(obs)
    hw.evaluate(node)
    assert hw.states()["decode_stall"] == "ok"
    # the production counter site (TextGenRunner.finalize and the sim
    # decode gate both call this ONE function)
    with use_obs(obs):
        count_decode_stall(2)
    node.chain.now = 5
    hw.evaluate(node)
    assert hw.states()["decode_stall"] == "firing"  # instant rule
    node.chain.now = 10
    hw.evaluate(node)  # no new stalls → resolves
    assert hw.states()["decode_stall"] == "resolved"
    (ev, _) = obs.journal.events(kind="alert_transition")
    assert ev["alert"] == "decode_stall"
    assert "zero-byte" in ev["detail"]


# -- text-stream simnet scenario (SIM101-113) -------------------------------

def test_text_stream_scenario_holds_all_invariants(tmp_path):
    """The text-stream flood (docs/fault-injection.md): FaultyTextRunner
    under decode-stall + slow-runner + latency faults. Every SIM
    invariant must hold, the injected decode_stall faults must raise
    the mapped healthwatch alert (SIM113 required direction), and the
    fault draws must never touch output bytes — same seed, same CIDs,
    faults on or off by construction."""
    from arbius_tpu.sim.harness import run_scenario
    from arbius_tpu.sim.invariants import check_all, classify_tasks
    from arbius_tpu.sim.scenario import SCENARIOS, get_scenario

    assert "text-stream" in SCENARIOS
    result = run_scenario(get_scenario("text-stream"), 7,
                          db_path=str(tmp_path / "text.sqlite"),
                          healthwatch=True)
    findings = check_all(result)
    assert findings == [], [f"{f.rule}: {f.message}" for f in findings]
    assert set(classify_tasks(result).values()) == {"claimed"}
    stalls = result.plane.fault_counts.get("decode_stall", 0)
    assert stalls > 0, "scenario must actually inject decode stalls"
    raised = {e["alert"] for e in result.journal_events
              if e.get("kind") == "alert_transition"}
    assert "decode_stall" in raised


def test_decode_stall_fault_is_in_the_coverage_map():
    from arbius_tpu.sim.invariants import FAULT_ALERTS

    assert FAULT_ALERTS["decode_stall"] == ("decode_stall",)


# -- e2e: the CID equality matrix through a real MinerNode ------------------

def _text_world(pipe, params, *, canonical_batch=2, pipeline_on=False,
                aot_dir=None):
    from arbius_tpu.chain import WAD, Engine, TokenLedger
    from arbius_tpu.node import (
        LocalChain,
        MinerNode,
        MiningConfig,
        ModelConfig,
        ModelRegistry,
        RegisteredModel,
    )
    from arbius_tpu.node.config import AotCacheConfig, PipelineConfig
    from arbius_tpu.templates.engine import load_template

    tok = TokenLedger()
    eng = Engine(tok, start_time=10_000)
    tok.mint(Engine.ADDRESS, 600_000 * WAD)
    miner, user = "0x" + "aa" * 20, "0x" + "01" * 20
    for a in (miner, user):
        tok.mint(a, 10**6 * WAD)
        tok.approve(a, Engine.ADDRESS, 10**30)
    mid = "0x" + eng.register_model(user, user, 0, b'{"f":"T"}').hex()
    registry = ModelRegistry()
    registry.register(RegisteredModel(
        id=mid, template=load_template("textgen"),
        runner=TextGenRunner(pipe, params)))
    chain = LocalChain(eng, miner)
    chain.validator_deposit(100 * WAD)
    node = MinerNode(
        chain,
        MiningConfig(models=(ModelConfig(id=mid, template="textgen"),),
                     canonical_batch=canonical_batch,
                     compile_cache_dir=None,
                     pipeline=PipelineConfig(enabled=pipeline_on),
                     aot_cache=AotCacheConfig(enabled=True, dir=aot_dir)
                     if aot_dir else AotCacheConfig()),
        registry)
    node.boot(skip_self_test=True)
    return eng, node, mid, user


def _drive(eng, node, mid, user):
    """Submit 4 tasks (both samplers, two budgets inside one decode
    bucket) and tick to quiescence; returns {taskid: cid}."""
    while node.tick():
        pass
    for i in range(4):
        obj = {"prompt": f"matrix task {i}",
               "max_new_tokens": (3, 4)[i % 2],
               "sampler": ("greedy", "top_k")[i % 2]}
        eng.submit_task(user, 0, user, bytes.fromhex(mid[2:]),
                        (1 + i) * 10**18, json.dumps(
                            obj, sort_keys=True).encode())
    for _ in range(128):
        if node.tick() == 0:
            break
    cids = {"0x" + t.hex(): "0x" + s.cid.hex()
            for t, s in eng.solutions.items()}
    node.close()
    return cids


def test_e2e_cid_matrix_pipeline_aot_mesh(tmp_path):
    """The acceptance matrix (docs/text-serving.md): a text task solves
    end to end through MinerNode with byte-identical CIDs across
    pipeline on/off × AOT off/cold/warm × mesh-off/dp2. Every world
    builds a FRESH pipeline instance (fresh executable cache) over the
    same params, so the AOT warm world genuinely deserializes."""
    from arbius_tpu.parallel import MeshSpec, build_mesh

    cfg = TextGenConfig.tiny()

    def fresh_pipe(mesh=None):
        return TextGenPipeline(cfg, mesh=mesh, prompt_buckets=P_EDGES,
                               decode_buckets=T_EDGES, top_k=4)

    params = fresh_pipe().init_params(seed=0)
    aot = str(tmp_path / "aot")

    def world(label, **kw):
        mesh = kw.pop("mesh", None)
        p = fresh_pipe(mesh)
        pl = p.place_params(params) if mesh is not None else params
        cids = _drive(*_text_world(p, pl, **kw))
        assert len(cids) == 4, f"{label}: {len(cids)}/4 solved"
        return cids

    base = world("baseline")
    assert world("pipeline-on", pipeline_on=True) == base
    assert world("aot-cold", aot_dir=aot) == base
    assert world("aot-warm", aot_dir=aot) == base
    mesh = build_mesh(MeshSpec(dp=2), devices=jax.devices()[:2])
    assert world("dp2", mesh=mesh) == base


def test_empty_decode_counts_stall_but_still_commits(pipe, params):
    """A zero-byte answer is a VALID solve (docs/text-serving.md):
    finalize counts arbius_decode_stalls_total and returns the empty
    artifact unchanged — never a retry, never a mutation."""
    from arbius_tpu.obs import Obs, use_obs

    r = TextGenRunner(pipe, params)
    obs = Obs()
    # drive finalize directly with tokens that detokenize to nothing
    tokens = np.full((1, 4), pipe.EOS_ID, np.int32)
    with use_obs(obs):
        out = r.finalize((tokens, [4]), 1)
    assert out == [{"out-1.txt": b""}]
    assert obs.registry.counter(
        "arbius_decode_stalls_total").value() == 1
