"""node/retry.py under the sim virtual clock (ISSUE satellite).

expretry is the node's universal failure envelope; under simnet every
sleep it takes is virtual chain time. These tests pin the exact policy:
the `base**attempt` curve, the `max_delay` cap, exhaustion's attempt
count, and — the simnet property — that a SimRng-driven flaky callee
produces an identical backoff timeline for an identical seed.
"""
from __future__ import annotations

import pytest

from arbius_tpu.chain.engine import Engine
from arbius_tpu.node.retry import BASE, RetriesExhausted, expretry
from arbius_tpu.sim.clock import VirtualClock
from arbius_tpu.sim.rng import SimRng


def _clock():
    return VirtualClock(Engine(start_time=50_000))


def test_backoff_curve_is_the_reference_sequence():
    clock = _clock()
    with pytest.raises(RetriesExhausted) as exc:
        expretry(lambda: 1 / 0, tries=5, sleep=clock.sleep, op="t")
    assert exc.value.attempts == 5
    assert isinstance(exc.value.last, ZeroDivisionError)
    # base**attempt for attempts 0..3; no sleep after the final failure
    assert clock.sleeps == [1.0, 1.5, 2.25, 3.375]
    # virtual chain time advanced by the ceil'd sum, never wall time
    assert clock.engine.now == 50_000 + 1 + 2 + 3 + 4


def test_max_delay_caps_the_curve():
    clock = _clock()
    with pytest.raises(RetriesExhausted):
        expretry(lambda: 1 / 0, tries=8, max_delay=3.0,
                 sleep=clock.sleep, op="t")
    assert clock.sleeps == [1.0, 1.5, 2.25, 3.0, 3.0, 3.0, 3.0]
    assert max(clock.sleeps) == 3.0


def test_flaky_callee_timeline_is_deterministic_in_seed():
    def timeline(seed: int) -> tuple[list[float], int]:
        clock = _clock()
        rng = SimRng(seed, "flaky-endpoint")
        calls = {"n": 0}

        def flaky():
            calls["n"] += 1
            if rng.chance(0.6):
                raise OSError("sim: endpoint 503")
            return "ok"

        assert expretry(flaky, tries=10, max_delay=4.0,
                        sleep=clock.sleep, op="t") == "ok"
        return clock.sleeps, calls["n"]

    a_sleeps, a_calls = timeline(7)
    b_sleeps, b_calls = timeline(7)
    assert (a_sleeps, a_calls) == (b_sleeps, b_calls)
    # every injected delay obeys the capped reference curve
    for i, s in enumerate(a_sleeps):
        assert s == min(BASE ** i, 4.0)
    # a different seed draws a different failure pattern somewhere in
    # the first few seeds (guards against the rng being constant)
    assert any(timeline(s)[0] != a_sleeps for s in range(1, 5))


def test_success_first_try_sleeps_nothing():
    clock = _clock()
    assert expretry(lambda: 42, sleep=clock.sleep) == 42
    assert clock.sleeps == []
    assert clock.engine.now == 50_000


def test_sim_rng_streams_are_independent_and_stable():
    a = SimRng(3, "x")
    b = SimRng(3, "x")
    assert [a.u64() for _ in range(5)] == [b.u64() for _ in range(5)]
    c = SimRng(3).stream("y")
    d = SimRng(3).stream("z")
    assert [c.u64() for _ in range(5)] != [d.u64() for _ in range(5)]
    assert SimRng(3, "x").randint(1, 3) in (1, 2, 3)
    assert not SimRng(0).chance(0.0)   # zero rate consumes no draw
    assert SimRng(0).chance(1.0)
