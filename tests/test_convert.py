"""Checkpoint-conversion tests: completeness (every UNet leaf maps to a
diffusers key), bijectivity (export → convert is the identity), and loud
failure on shape mismatches. Numeric validation against real published
weights is a deployment step (zero-egress here); the boot self-test's
golden CID is the production arbiter.
"""
from __future__ import annotations

import jax
import numpy as np
import pytest

from arbius_tpu.models.sd15 import ByteTokenizer, SD15Config, SD15Pipeline
from arbius_tpu.models.sd15.convert import (
    ConversionError,
    convert_sd15_unet,
    export_sd15_unet,
    unet_key_for,
)

pytestmark = [pytest.mark.slow, pytest.mark.model]


@pytest.fixture(scope="module")
def unet_params():
    pipe = SD15Pipeline(SD15Config.tiny(),
                        tokenizer=ByteTokenizer(max_length=16, bos_id=257,
                                                eos_id=258))
    return pipe.init_params(seed=3)["unet"]


def test_every_leaf_is_mapped(unet_params):
    paths = []
    jax.tree_util.tree_map_with_path(
        lambda p, _: paths.append("/".join(
            str(getattr(k, "key", getattr(k, "idx", k))) for k in p)),
        unet_params)
    for p in paths:
        key, tf = unet_key_for(p, n_levels=4)
        assert key and callable(tf)


def test_export_convert_roundtrip(unet_params):
    sd = export_sd15_unet(unet_params)
    # exported dict looks like a diffusers checkpoint
    assert any(k.startswith("down_blocks.0.resnets.0.") for k in sd)
    assert any(k.startswith("mid_block.attentions.0.transformer_blocks.0.")
               for k in sd)
    assert "time_embedding.linear_1.weight" in sd
    # fused GEGLU was reassembled
    assert any(k.endswith("ff.net.0.proj.weight") for k in sd)

    back = convert_sd15_unet(sd, unet_params)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_array_equal(np.asarray(a),
                                                   np.asarray(b)),
        unet_params, back)


def test_converted_params_drive_the_model(unet_params):
    """Converted tree is structurally valid for the flax module."""
    import jax.numpy as jnp

    from arbius_tpu.models.sd15.unet import UNet2DCondition, UNetConfig

    back = convert_sd15_unet(export_sd15_unet(unet_params), unet_params)
    model = UNet2DCondition(UNetConfig.tiny())
    x = jnp.zeros((1, 8, 8, 4))
    ctx = jnp.zeros((1, 4, 16))
    a = model.apply({"params": unet_params}, x, jnp.ones((1,)), ctx)
    b = model.apply({"params": back}, x, jnp.ones((1,)), ctx)
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_missing_keys_fail_loudly(unet_params):
    sd = export_sd15_unet(unet_params)
    sd.pop("conv_in.weight")
    with pytest.raises(ConversionError, match="missing"):
        convert_sd15_unet(sd, unet_params)


def test_shape_mismatch_fails_loudly(unet_params):
    sd = export_sd15_unet(unet_params)
    sd["conv_in.weight"] = np.zeros((1, 2, 3, 4), np.float32)
    with pytest.raises(ConversionError, match="converted shape"):
        convert_sd15_unet(sd, unet_params)


def _synthesize(template, key_for):
    """Build a diffusers-style state dict whose conversion reproduces the
    template tree exactly (per-leaf inverse of the declared transform)."""
    sd = {}

    def visit(path, leaf):
        p = "/".join(str(getattr(k, "key", getattr(k, "idx", k)))
                     for k in path)
        key, tf = key_for(p)
        w = np.asarray(leaf)
        name = getattr(tf, "__name__", "")
        if name == "_conv":
            sd[key] = np.transpose(w, (3, 2, 0, 1))
        elif name == "_linear":
            sd[key] = np.transpose(w)
        elif name == "_ident":
            sd[key] = w
        else:  # head-layout lambdas: invert reshape/transpose
            if w.ndim == 3 and key.endswith("out_proj.weight"):
                sd[key] = np.transpose(w.reshape(-1, w.shape[-1]))
            elif w.ndim == 3:   # (in, heads, head_dim) qkv kernel
                sd[key] = np.transpose(w.reshape(w.shape[0], -1))
            elif w.ndim == 2 and "bias" in key:   # (heads, head_dim)
                sd[key] = w.reshape(-1)
            else:
                raise AssertionError(f"unexpected leaf for {key}")

    jax.tree_util.tree_map_with_path(visit, template)
    return sd


def test_vae_conversion_roundtrip():
    from arbius_tpu.models.sd15.convert import convert_sd15_vae, vae_key_for

    pipe = SD15Pipeline(SD15Config.tiny(),
                        tokenizer=ByteTokenizer(max_length=16, bos_id=257,
                                                eos_id=258))
    vae_params = pipe.init_params(seed=1)["vae"]
    sd = _synthesize(vae_params, lambda p: vae_key_for(p, 4))
    assert "decoder.mid_block.attentions.0.to_q.weight" in sd
    assert "post_quant_conv.weight" in sd
    back = convert_sd15_vae(sd, vae_params)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_array_equal(np.asarray(a),
                                                   np.asarray(b)),
        vae_params, back)


def test_text_conversion_roundtrip():
    from arbius_tpu.models.sd15.convert import (
        convert_sd15_text,
        text_key_for,
    )

    cfg = SD15Config.tiny()
    pipe = SD15Pipeline(cfg, tokenizer=ByteTokenizer(max_length=16,
                                                     bos_id=257, eos_id=258))
    text_params = pipe.init_params(seed=2)["text"]
    heads = cfg.text.heads
    head_dim = cfg.text.width // heads
    sd = _synthesize(text_params, lambda p: text_key_for(p, heads, head_dim))
    assert "text_model.encoder.layers.0.self_attn.q_proj.weight" in sd
    assert "text_model.embeddings.token_embedding.weight" in sd
    back = convert_sd15_text(sd, text_params, heads, head_dim)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_array_equal(np.asarray(a),
                                                   np.asarray(b)),
        text_params, back)


def test_geglu_split_order_matches_diffusers(unet_params):
    """diffusers GEGLU chunks proj output as (value, gate) — our ff_val
    must take the FIRST half."""
    sd = export_sd15_unet(unet_params)
    key = next(k for k in sd if k.endswith("ff.net.0.proj.weight"))
    fused = sd[key]
    back = convert_sd15_unet(sd, unet_params)
    # locate the corresponding ff_val kernel in the tree
    def find(node, name):
        for k, v in node.items():
            if k == name:
                return v
            if isinstance(v, dict):
                got = find(v, name)
                if got is not None:
                    return got
        return None
    val = np.asarray(find(back, "ff_val")["kernel"])
    np.testing.assert_array_equal(val, np.transpose(fused[:fused.shape[0] // 2]))
