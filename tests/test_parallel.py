"""Parallel layer tests on the virtual 8-device CPU mesh."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from arbius_tpu.parallel import (
    MeshSpec,
    all_gather_seq,
    batch_sharding,
    build_mesh,
    halo_exchange,
    local_mesh,
    ring_pass,
    shard_params,
)
from arbius_tpu.parallel.sharding import DEFAULT_TP_RULES

pytestmark = [pytest.mark.slow, pytest.mark.model]


def test_devices_virtualized():
    assert len(jax.devices()) == 8


def test_meshspec_resolve_wildcard():
    assert MeshSpec().resolve(8) == {"pp": 1, "dp": 8, "sp": 1, "tp": 1}
    assert MeshSpec(dp=-1, tp=2).resolve(8) == {"pp": 1, "dp": 4, "sp": 1, "tp": 2}
    assert MeshSpec(dp=2, sp=2, tp=2).resolve(8) == {"pp": 1, "dp": 2, "sp": 2, "tp": 2}


def test_meshspec_resolve_errors():
    with pytest.raises(ValueError):
        MeshSpec(dp=3, tp=2).resolve(8)  # 6 != 8
    with pytest.raises(ValueError):
        MeshSpec(dp=-1, tp=3).resolve(8)  # 8 % 3


def test_build_mesh_shapes():
    mesh = build_mesh(MeshSpec(dp=2, sp=2, tp=2))
    assert dict(mesh.shape) == {"pp": 1, "dp": 2, "sp": 2, "tp": 2}
    mesh = local_mesh(4)
    assert dict(mesh.shape) == {"pp": 1, "dp": 4, "sp": 1, "tp": 1}


def test_batch_sharding_places_shards():
    mesh = build_mesh(MeshSpec(dp=8))
    x = jnp.arange(16.0).reshape(16, 1)
    xs = jax.device_put(x, batch_sharding(mesh, x.ndim))
    assert len(xs.addressable_shards) == 8
    assert xs.addressable_shards[0].data.shape == (2, 1)
    np.testing.assert_array_equal(np.asarray(xs), np.asarray(x))


def test_shard_params_tp_rules():
    mesh = build_mesh(MeshSpec(dp=4, tp=2))
    params = {
        "blk": {"to_q": {"kernel": jnp.ones((8, 16))},
                "to_out": {"kernel": jnp.ones((16, 8))}},
        "other": {"kernel": jnp.ones((3, 3))},
    }
    out = shard_params(params, mesh, DEFAULT_TP_RULES)
    q = out["blk"]["to_q"]["kernel"]
    o = out["blk"]["to_out"]["kernel"]
    # tp=2: q sharded on out-dim, o on in-dim, other replicated
    assert q.sharding.spec == P(None, "tp")
    assert o.sharding.spec == P("tp", None)
    assert out["other"]["kernel"].sharding.spec == P()


def test_tp_rules_hit_real_sd15_param_tree():
    """Every rule must match real flax param paths — synthetic-path tests
    can't catch a dead rule (a regex written for auto-names that the model
    never produces silently replicates the weight)."""
    import re

    from arbius_tpu.models.sd15 import SD15Config, SD15Pipeline, ByteTokenizer
    from arbius_tpu.parallel.sharding import _path_str

    pipe = SD15Pipeline(SD15Config.tiny(),
                        tokenizer=ByteTokenizer(max_length=16,
                                                bos_id=257, eos_id=258))
    params = pipe.init_params(seed=0)
    paths = []
    jax.tree_util.tree_map_with_path(
        lambda p, _: paths.append(_path_str(p)), params)
    for pat, _ in DEFAULT_TP_RULES:
        hits = [p for p in paths if re.match(pat, p)]
        assert hits, f"TP rule {pat!r} matches nothing in the SD15 tree"


def test_shard_params_skips_indivisible():
    mesh = build_mesh(MeshSpec(dp=4, tp=2))
    params = {"to_q": {"kernel": jnp.ones((8, 3))}}  # 3 % 2 != 0
    out = shard_params(params, mesh, DEFAULT_TP_RULES)
    assert out["to_q"]["kernel"].sharding.spec == P()


def _shard_map(fn, mesh, in_specs, out_specs):
    return jax.shard_map(fn, mesh=mesh, in_specs=in_specs,
                         out_specs=out_specs, check_vma=False)


def test_all_gather_seq_roundtrip():
    mesh = build_mesh(MeshSpec(dp=1, sp=8, tp=1))
    x = jnp.arange(32.0).reshape(16, 2)

    fn = _shard_map(
        lambda a: all_gather_seq(a, "sp", axis=0),
        mesh, in_specs=P("sp", None), out_specs=P(None, None))
    np.testing.assert_array_equal(np.asarray(fn(x)), np.asarray(x))


def test_ring_pass_rotates():
    mesh = build_mesh(MeshSpec(dp=1, sp=8, tp=1))
    x = jnp.arange(8.0).reshape(8, 1)
    fn = _shard_map(lambda a: ring_pass(a, "sp"), mesh,
                    in_specs=P("sp", None), out_specs=P("sp", None))
    out = np.asarray(fn(x)).ravel()
    # device i's value moves to device i+1 -> output shard i holds x[i-1]
    np.testing.assert_array_equal(out, np.roll(np.arange(8.0), 1))


def test_halo_exchange_matches_zero_padding():
    mesh = local_mesh(4, MeshSpec(dp=1, sp=4, tp=1))
    frames = jnp.arange(16.0).reshape(16, 1)  # 4 frames per device
    halo = 2

    fn = _shard_map(
        lambda a: halo_exchange(a, "sp", axis=0, halo=halo),
        mesh, in_specs=P("sp", None), out_specs=P("sp", None))
    out = np.asarray(fn(frames))  # [4*(4+2*2), 1] = [32, 1]
    shards = out.reshape(4, 4 + 2 * halo)
    full = np.pad(np.arange(16.0), halo)
    for i in range(4):
        np.testing.assert_array_equal(shards[i], full[i * 4:i * 4 + 4 + 2 * halo])


def test_dp_inference_deterministic():
    """The determinism contract is run-to-run bit-equality of the SAME
    compiled program (SURVEY.md §7 hard part 1) — assert that for a
    dp-sharded graph, and numerical closeness to the eager reference
    (jit/eager bit-equality is NOT promised; fusion changes rounding)."""
    mesh = build_mesh(MeshSpec(dp=8))
    w = jnp.ones((4, 4)) * 0.5
    x = jax.random.normal(jax.random.PRNGKey(0), (16, 4))

    def step(w, x):
        return jnp.tanh(x @ w)

    xs = jax.device_put(x, batch_sharding(mesh, 2))
    ws = jax.device_put(w, jax.sharding.NamedSharding(mesh, P()))
    fn = jax.jit(step)
    got1 = np.asarray(fn(ws, xs))
    got2 = np.asarray(fn(ws, xs))
    np.testing.assert_array_equal(got1, got2)
    np.testing.assert_allclose(got1, np.asarray(step(w, x)), rtol=1e-6)


def test_halo_exchange_rejects_oversize_halo():
    mesh = local_mesh(4, MeshSpec(dp=1, sp=4, tp=1))
    frames = jnp.arange(4.0).reshape(4, 1)  # 1 frame per device
    fn = _shard_map(
        lambda a: halo_exchange(a, "sp", axis=0, halo=2),
        mesh, in_specs=P("sp", None), out_specs=P("sp", None))
    with pytest.raises(ValueError, match="halo"):
        fn(frames)


def test_pipeline_apply_matches_sequential():
    """GPipe-style pp over 4 stages == sequential composition, exactly."""
    import flax.linen as nn

    from arbius_tpu.parallel import (
        MeshSpec,
        build_mesh,
        pipeline_apply,
        stack_stage_params,
    )

    mesh = build_mesh(MeshSpec(pp=4, dp=1), devices=jax.devices()[:4])

    class Layer(nn.Module):
        @nn.compact
        def __call__(self, x):
            return nn.tanh(nn.Dense(8, dtype=jnp.float32)(x))

    layer = Layer()
    x = jax.random.normal(jax.random.PRNGKey(0), (8, 8))
    trees = [layer.init(jax.random.PRNGKey(i), x)["params"]
             for i in range(4)]
    stacked = stack_stage_params(trees)

    def fn(params, h):
        return layer.apply({"params": params}, h)

    got = pipeline_apply(fn, stacked, x, mesh)
    want = x
    for tr in trees:
        want = fn(tr, want)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-6, atol=1e-6)


def test_pipeline_composes_with_dp():
    """pp=2 × dp=2: microbatch batch dim sharded over dp, stages over pp."""
    import flax.linen as nn

    from arbius_tpu.parallel import (
        MeshSpec,
        build_mesh,
        pipeline_apply,
        stack_stage_params,
    )

    mesh = build_mesh(MeshSpec(pp=2, dp=2), devices=jax.devices()[:4])

    class Layer(nn.Module):
        @nn.compact
        def __call__(self, x):
            return nn.Dense(4, dtype=jnp.float32)(x)

    layer = Layer()
    x = jax.random.normal(jax.random.PRNGKey(1), (8, 4))
    trees = [layer.init(jax.random.PRNGKey(10 + i), x)["params"]
             for i in range(2)]

    def fn(params, h):
        return layer.apply({"params": params}, h)

    got = pipeline_apply(fn, stack_stage_params(trees), x, mesh,
                         microbatches=4, batch_axis="dp")
    want = fn(trees[1], fn(trees[0], x))
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-6, atol=1e-6)
