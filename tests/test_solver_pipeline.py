"""Chunk-pipelined solve path: solve_files_batch must overlap host
encode with the next dispatch WITHOUT changing output order or bytes."""
from __future__ import annotations

from arbius_tpu.node.solver import RegisteredModel, solve_files_batch


class _Template:
    outputs = [type("O", (), {"filename": "out-1.png", "type": "image"})()]


class _PipelinedRunner:
    """Fake runner recording the dispatch/finalize schedule."""

    def __init__(self, log):
        self.log = log

    def __call__(self, hydrated, seed):
        return self.run_batch([(hydrated, seed)])[0]

    def run_batch(self, items):
        return self.finalize(self.dispatch(items), len(items))

    def dispatch(self, items):
        self.log.append(("dispatch", tuple(s for _, s in items)))
        return [f"img{s}".encode() for _, s in items]

    def finalize(self, dev, n_real):
        self.log.append(("finalize", tuple(dev[:n_real])))
        return [{"out-1.png": dev[i]} for i in range(n_real)]


def _model(log):
    return RegisteredModel(id="0x00", template=_Template(),
                           runner=_PipelinedRunner(log))


def test_pipeline_overlaps_and_preserves_order():
    log = []
    items = [({"prompt": f"p{i}"}, i) for i in range(7)]
    out = solve_files_batch(_model(log), items, canonical_batch=2)
    # bytes + order identical to the serial path
    assert [f["out-1.png"] for f in out] == [f"img{i}".encode()
                                            for i in range(7)]
    # schedule actually overlaps: chunk 2's dispatch precedes chunk 1's
    # finalize (one-deep pipeline), incl. the padded last chunk
    kinds = [k for k, _ in log]
    assert kinds == ["dispatch", "dispatch", "finalize", "dispatch",
                     "finalize", "dispatch", "finalize", "finalize"]
    # padding repeats the last item but only the real result surfaces
    assert log[-1] == ("finalize", (b"img6",))


def test_single_chunk_stays_serial():
    log = []
    items = [({"prompt": "p"}, 1), ({"prompt": "q"}, 2)]
    out = solve_files_batch(_model(log), items, canonical_batch=2)
    assert [f["out-1.png"] for f in out] == [b"img1", b"img2"]
    assert [k for k, _ in log] == ["dispatch", "finalize"]
