"""contestationVoteFinish automation + profitability gate (VERDICT #8).

The reference stubs processContestationVoteFinish
(`miner/src/index.ts:392-395` — "not implemented yet"), stranding every
participant's escrowed slash stake until a human calls finish. Here the
node schedules and executes the finish itself, from both sides of a
contestation (contester and accused).
"""
from __future__ import annotations

import json

from arbius_tpu.chain import WAD
from arbius_tpu.node import LocalChain
from tests.test_node import (
    MINER,
    OTHER,
    USER,
    build_world,
    drain,
    submit,
    task_input,
)


def _wrong_solution(eng, other_chain, tid_hex):
    """OTHER commits+reveals a deliberately wrong CID for the task."""
    bad_cid = "0x1220" + "ee" * 32
    commitment = other_chain.generate_commitment(tid_hex, bad_cid)
    other_chain.signal_commitment(commitment)
    other_chain.submit_solution(tid_hex, bad_cid)


def test_contester_path_finishes_vote_and_refunds_escrow():
    eng, tok, chain, node, mid = build_world()
    from arbius_tpu.chain import Engine

    # slashing only bites once supply has been emitted from the engine
    # (getPsuedoTotalSupply, EngineV1.sol:521-527): simulate 100k emitted
    tok.transfer(Engine.ADDRESS, USER, 100_000 * WAD)
    assert eng.get_slash_amount() > 0
    other = LocalChain(eng, OTHER)
    other.validator_deposit(100 * WAD)
    third = LocalChain(eng, USER)
    third.validator_deposit(100 * WAD)
    # age the stakes past the anti-vote-buying gate (EngineV1.sol:976-981)
    eng.advance_time(eng.max_contestation_validator_stake_since + 100)

    tid = submit(eng, mid, "contested")
    _wrong_solution(eng, other, tid)
    drain(node)  # node solves, sees wrong CID on-chain → contests
    tid_b = bytes.fromhex(tid[2:])
    assert tid_b in eng.contestations
    assert node.metrics.contestations_submitted == 1
    assert node.db.has_job("voteFinish", {"taskid": tid})
    third.vote_on_contestation(tid, True)  # 2 yeas vs 1 nay: contest wins

    staked_before = chain.validator_staked()  # escrow held: slash deducted
    eng.advance_time(eng.min_contestation_vote_period_time + 200)
    drain(node)
    assert node.metrics.vote_finishes == 1
    con = eng.contestations[tid_b]
    assert con.finish_start_index > 0          # payout loop ran
    # winning contester: escrow refunded (+ half the nays' slash as token)
    assert chain.validator_staked() > staked_before


def test_accused_path_schedules_finish():
    eng, tok, chain, node, mid = build_world()
    other = LocalChain(eng, OTHER)
    other.validator_deposit(100 * WAD)

    tid = submit(eng, mid, "we answer first", fee=10 * WAD)
    drain(node)  # node solves correctly
    tid_b = bytes.fromhex(tid[2:])
    assert eng.solutions[tid_b].validator == MINER
    # OTHER contests our (correct) solution; engine auto-nay-votes for us
    other.submit_contestation(tid)
    assert node.db.has_job("voteFinish", {"taskid": tid})

    balance_before = tok.balance_of(MINER)
    eng.advance_time(eng.min_contestation_vote_period_time + 200)
    drain(node)
    assert node.metrics.vote_finishes == 1
    # tie (1 yea vs 1 nay) sides with nays: solution stands and the finish
    # path pays the solver its fee (without flipping `claimed` — the
    # contract's finish calls _claimSolutionFeesAndReward directly,
    # EngineV1.sol:1097-1100)
    assert eng.contestations[tid_b].finish_start_index > 0
    assert tok.balance_of(MINER) > balance_before


def test_vote_finish_not_duplicated():
    eng, tok, chain, node, mid = build_world()
    other = LocalChain(eng, OTHER)
    other.validator_deposit(100 * WAD)
    tid = submit(eng, mid, "dup check")
    _wrong_solution(eng, other, tid)
    drain(node)
    jobs = [j for j in node.db.get_jobs(now=2**62)
            if j.method == "voteFinish"]
    assert len(jobs) == 1


def test_profitability_gate_skips_cheap_tasks():
    eng, tok, chain, node, mid = build_world(
        min_fee_per_second=WAD, assumed_solve_seconds=10.0)
    tid_cheap = submit(eng, mid, "cheap", fee=0)
    drain(node)
    assert bytes.fromhex(tid_cheap[2:]) not in eng.solutions
    assert node.metrics.tasks_unprofitable == 1

    tid_rich = submit(eng, mid, "rich", fee=20 * WAD)
    drain(node)
    assert bytes.fromhex(tid_rich[2:]) in eng.solutions
    assert node.metrics.tasks_unprofitable == 1


def test_profitability_gate_disabled_by_default():
    eng, tok, chain, node, mid = build_world()
    tid = submit(eng, mid, "free", fee=0)
    drain(node)
    assert bytes.fromhex(tid[2:]) in eng.solutions
    assert node.metrics.tasks_unprofitable == 0
