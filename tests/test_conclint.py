"""conclint — thread topology, interprocedural locksets, CONC4xx rule
fixtures, the runtime witness, and the tier-1 self-check.

The self-check is the standing gate: conclint over `arbius_tpu/`
against `conclint-baseline.json` must report zero unwaived findings —
add an unlocked cross-thread attribute to the node and THIS file goes
red. The injected-race regression proves the gate can actually catch
one, both halves: the static CONC401 (waivers stripped) and the simnet
runtime witness (SIM110).
"""
from __future__ import annotations

import json
import os
import pathlib
import subprocess
import sys

import pytest

from arbius_tpu.analysis import Baseline
from arbius_tpu.analysis import baseline as baseline_mod
from arbius_tpu.analysis.conc import (
    CONC_RULE_IDS,
    analyze_conc_sources,
    analyze_conc_tree,
)
from arbius_tpu.analysis.conc.cli import main as cli_main
from arbius_tpu.analysis.conc.witness import (
    ConcWitness,
    annotate_findings,
    crosscheck,
    order_cycle,
)
from arbius_tpu.analysis.core import KNOWN_EXTERNAL_RULES

REPO = pathlib.Path(__file__).resolve().parent.parent
FIXDIR = pathlib.Path(__file__).parent / "fixtures" / "conclint"

sys.path.insert(0, str(REPO / "tools"))


def rules_of(findings):
    return [f.rule for f in findings]


def check(src: str, path: str = "m.py"):
    findings, _ = analyze_conc_sources({path: src})
    return findings


_THREADED = """\
import threading

class Worker:
    def __init__(self):
        self.state = "idle"
        self._t = %s

    def poke(self, s):
        self.state = s

    def _run(self):
        while self.state != "stop":
            pass
"""


# -- thread topology --------------------------------------------------------

def test_topology_thread_timer_and_positional_spawns():
    for spawn in ("threading.Thread(target=self._run)",
                  "threading.Thread(None, self._run)",
                  "threading.Timer(5.0, self._run)",
                  "threading.Timer(interval=1.0, function=self._run)"):
        findings = check(_THREADED % spawn)
        assert rules_of(findings) == ["CONC401"], spawn


def test_topology_thread_subclass_run_is_a_root():
    src = ("import threading\n"
           "class W(threading.Thread):\n"
           "    def __init__(self):\n"
           "        super().__init__(daemon=True)\n"
           "        self.cmd = None\n"
           "    def send(self, c):\n"
           "        self.cmd = c\n"
           "    def run(self):\n"
           "        while self.cmd != 'stop':\n"
           "            pass\n")
    assert rules_of(check(src)) == ["CONC401"]


def test_topology_http_handler_methods_are_pooled_roots():
    # BaseHTTPRequestHandler do_* methods run on server threads; the
    # handler pool races ITSELF (pooled root), so two do_GETs writing
    # one attribute with no lock is a finding
    src = ("from http.server import BaseHTTPRequestHandler\n"
           "class H(BaseHTTPRequestHandler):\n"
           "    def do_GET(self):\n"
           "        self.hits = getattr(self, 'hits', 0) + 1\n"
           "    def do_POST(self):\n"
           "        self.hits = 0\n")
    findings = check(src)
    assert "CONC401" in rules_of(findings)


def test_topology_cross_file_spawn_resolves_through_imports():
    srcs = {
        "pkg/__init__.py": "",
        "pkg/b.py": ("class Worker:\n"
                     "    def __init__(self):\n"
                     "        self.count = 0\n"
                     "    def loop(self):\n"
                     "        while True:\n"
                     "            self.count += 1\n"
                     "    def read(self):\n"
                     "        return self.count\n"),
        "pkg/a.py": ("import threading\n"
                     "from pkg.b import Worker\n"
                     "def go():\n"
                     "    w = Worker()\n"
                     "    threading.Thread(target=w.loop).start()\n"
                     "    while True:\n"
                     "        print(w.read())\n"),
    }
    findings, prog = analyze_conc_sources(srcs)
    assert rules_of(findings) == ["CONC401"]
    assert findings[0].path == "pkg/b.py"
    assert prog.func_roots("pkg.b.Worker.loop") == {"pkg.b.Worker.loop"}
    assert "main" in prog.func_roots("pkg.b.Worker.read")


def test_topology_package_reexport_alias_chases_to_definer():
    srcs = {
        "pkg/__init__.py": "from pkg.impl import Node\n",
        "pkg/impl.py": ("import threading\n"
                        "class Node:\n"
                        "    def __init__(self):\n"
                        "        self.v = 0\n"
                        "        t = threading.Thread(target=self.bg)\n"
                        "    def bg(self):\n"
                        "        self.v += 1\n"
                        "    def get(self):\n"
                        "        return self.v\n"),
        "main.py": ("from pkg import Node\n"
                    "def run():\n"
                    "    n = Node()\n"
                    "    return n.get()\n"),
    }
    findings, prog = analyze_conc_sources(srcs)
    # main.py's `n.get()` resolved through the package re-export: get
    # runs on the main root, bg on its thread root → the race is seen
    assert rules_of(findings) == ["CONC401"]


# -- locksets ---------------------------------------------------------------

def test_lockset_lock_on_both_sides_is_clean():
    src = _THREADED % "threading.Thread(target=self._run)"
    src = src.replace("        self.state = s",
                      "        with self._lock:\n"
                      "            self.state = s")
    src = src.replace('        while self.state != "stop":\n            pass',
                      "        with self._lock:\n"
                      "            s = self.state")
    src = src.replace('        self.state = "idle"',
                      '        self.state = "idle"\n'
                      "        self._lock = threading.Lock()")
    assert not check(src)


def test_lockset_interprocedural_held_at_every_call_site():
    # the NodeDB._commit pattern: the helper has no lexical lock but
    # every caller holds it — proved clean, not waived
    src = ("import threading\n"
           "import sqlite3\n"
           "class DB:\n"
           "    def __init__(self):\n"
           "        self._conn = sqlite3.connect(':memory:')\n"
           "        self._lock = threading.Lock()\n"
           "    def put(self, x):\n"
           "        with self._lock:\n"
           "            self._conn.execute('INSERT INTO t VALUES (?)', (x,))\n"
           "            self._commit()\n"
           "    def _commit(self):\n"
           "        self._conn.commit()\n")
    findings, prog = analyze_conc_sources({"db.py": src})
    assert not findings
    assert prog.held["db.DB._commit"] == {"db.DB._lock"}
    # ...but ONE unlocked call site breaks the proof
    src2 = src + ("    def sneak(self):\n"
                  "        self._commit()\n")
    findings, prog = analyze_conc_sources({"db.py": src2})
    assert prog.held["db.DB._commit"] == frozenset()
    assert "CONC404" in rules_of(findings)


def test_lockset_acquire_release_spans():
    src = ("import threading\n"
           "import time\n"
           "L = threading.Lock()\n"
           "def f():\n"
           "    L.acquire()\n"
           "    time.sleep(1)\n"
           "    L.release()\n"
           "    time.sleep(2)\n")
    findings = check(src)
    # only the sleep between acquire and release is held
    assert rules_of(findings) == ["CONC403"]
    assert findings[0].line == 6


# -- CONC401 edges ----------------------------------------------------------

def test_conc401_init_and_sync_attrs_and_readonly_exempt():
    src = ("import threading\n"
           "class W:\n"
           "    def __init__(self):\n"
           "        self.stop = threading.Event()\n"
           "        self.name = 'w'\n"
           "        self._t = threading.Thread(target=self._run)\n"
           "    def _run(self):\n"
           "        while not self.stop.wait(1):\n"
           "            print(self.name)\n")
    assert not check(src)


def test_conc401_same_single_root_is_not_concurrent():
    src = ("class Plain:\n"
           "    def a(self):\n        self.x = 1\n"
           "    def b(self):\n        return self.x\n")
    assert not check(src)


def test_conc401_container_mutation_counts_as_write():
    src = _THREADED % "threading.Thread(target=self._run)"
    src = src.replace("    def poke(self, s):\n        self.state = s",
                      "    def poke(self, s):\n        self.state.add(s)")
    src = src.replace('        self.state = "idle"',
                      "        self.state = set()")
    src = src.replace('        while self.state != "stop":\n            pass',
                      "        for x in sorted(self.state):\n"
                      "            pass")
    assert rules_of(check(src)) == ["CONC401"]


# -- CONC402/403 edges ------------------------------------------------------

def test_conc402_consistent_order_is_clean():
    src = ("import threading\n"
           "A = threading.Lock()\n"
           "B = threading.Lock()\n"
           "def f():\n"
           "    with A:\n"
           "        with B:\n"
           "            pass\n"
           "def g():\n"
           "    with A:\n"
           "        with B:\n"
           "            pass\n")
    assert not check(src)


def test_conc403_wait_and_timeout_exemptions():
    src = ("import threading\n"
           "import queue\n"
           "class C:\n"
           "    def __init__(self):\n"
           "        self._cv = threading.Condition()\n"
           "        self._q = queue.Queue(8)\n"
           "        self.items = []\n"
           "    def take(self):\n"
           "        with self._cv:\n"
           "            while not self.items:\n"
           "                self._cv.wait()\n"       # releases the cv
           "            return self.items.pop()\n"
           "    def feed(self, x):\n"
           "        self._q.put(x, timeout=5)\n")    # bounded, no lock
    assert not [f for f in check(src) if f.rule == "CONC403"]
    # but wait() while ALSO holding another lock is a stall
    src2 = src.replace("        self._q = queue.Queue(8)",
                       "        self._q = queue.Queue(8)\n"
                       "        self._lock = threading.Lock()")
    src2 = src2.replace("        with self._cv:\n",
                        "        with self._lock:\n"
                        "            pass\n"
                        "        with self._cv:\n")
    src3 = ("import threading\n"
            "class D:\n"
            "    def __init__(self):\n"
            "        self._cv = threading.Condition()\n"
            "        self._lock = threading.Lock()\n"
            "        self.items = []\n"
            "    def take(self):\n"
            "        with self._lock:\n"
            "            with self._cv:\n"
            "                self._cv.wait()\n")
    hits = [f for f in check(src3) if f.rule == "CONC403"]
    assert len(hits) == 1 and "_lock" in hits[0].message


def test_conc403_unbounded_spellings_not_exempt():
    # block=True blocks forever, timeout=None is the unbounded default
    # spelled out, join(None) waits forever — none may pass as bounded
    base = ("import threading\nimport queue\n"
            "class C:\n"
            "    def __init__(self):\n"
            "        self._lock = threading.Lock()\n"
            "        self._q = queue.Queue(8)\n"
            "        self._t = threading.Thread(target=self.f)\n"
            "    def f(self):\n"
            "        with self._lock:\n"
            "            %s\n")
    for call in ("self._q.get(block=True)",
                 "self._q.get(timeout=None)",
                 "self._t.join(None)"):
        hits = [f for f in check(base % call) if f.rule == "CONC403"]
        assert len(hits) == 1, call
    for call in ("self._q.get(block=False)",
                 "self._q.get(timeout=5)",
                 "self._q.get(block=True, timeout=5)",
                 "self._t.join(2.0)"):
        assert not [f for f in check(base % call)
                    if f.rule == "CONC403"], call


# -- fixtures + golden ------------------------------------------------------

def test_fixture_pairs_positive_and_waived():
    findings, _, _ = analyze_conc_tree([str(FIXDIR / "races")],
                                       root=str(FIXDIR))
    assert rules_of(findings) == ["CONC401", "CONC402", "CONC403",
                                  "CONC403", "CONC404", "CONC405"]
    # every finding sits in a *_pos.py file — the waived twins absorbed
    assert all("_pos.py" in f.path for f in findings)


def test_fixture_golden_json():
    findings, _, _ = analyze_conc_tree([str(FIXDIR / "races")],
                                       root=str(FIXDIR))
    got = json.dumps(
        {"version": 1, "findings": [f.to_json() for f in findings]},
        indent=2, sort_keys=True) + "\n"
    assert got == (FIXDIR / "races.golden.json").read_text()


def test_two_runs_byte_identical():
    a, _, _ = analyze_conc_tree([str(REPO / "arbius_tpu")], root=str(REPO))
    b, _, _ = analyze_conc_tree([str(REPO / "arbius_tpu")], root=str(REPO))
    assert [f.to_json() for f in a] == [f.to_json() for f in b]


# -- the tier-1 self-check --------------------------------------------------

def test_package_self_check_clean_against_baseline():
    findings, _, _ = analyze_conc_tree([str(REPO / "arbius_tpu")],
                                       root=str(REPO))
    bl = Baseline.load(str(REPO / "conclint-baseline.json"))
    residue = bl.apply(findings)
    assert residue == [], (
        "conclint found non-waived findings — fix them, pragma them "
        "with a reason, or (if intentional) run tools/conclint.py "
        "--baseline-update and justify the new entries:\n"
        + "\n".join(f.text() for f in residue))


def test_baseline_entries_are_justified():
    doc = json.loads((REPO / "conclint-baseline.json").read_text())
    assert doc["findings"], "baseline should document the reviewed waivers"
    for e in doc["findings"]:
        assert e["reason"] and baseline_mod.UNREVIEWED not in e["reason"], \
            f"unjustified baseline entry: {e['path']} {e['rule']}"


def test_external_rule_ids_pinned_in_core():
    # detlint's LINT002 validator must know every conclint id, or a
    # conclint waiver pragma would be flagged as a typo
    assert set(CONC_RULE_IDS) <= KNOWN_EXTERNAL_RULES


def test_fixed_rpc_race_stays_fixed():
    """The PR's triage fix: the tick thread's scheduler-state mutations
    and the ControlRPC debug view share MinerNode.state_lock — a future
    edit dropping either side must re-surface the CONC401s."""
    findings, _, prog = analyze_conc_tree([str(REPO / "arbius_tpu")],
                                          root=str(REPO))
    flagged = {f.message.split("`")[1] for f in findings
               if f.rule == "CONC401"}
    for attr in ("CostModel.rows", "CostSched._warm", "CostSched._last",
                 "MinerNode.solve_layout"):
        assert attr not in flagged, f"{attr} race regressed"
    # and the lock discipline is visible to the analyzer
    assert prog.held["arbius_tpu.node.sched.CostSched.mark_warm"] == \
        {"arbius_tpu.node.node.MinerNode.state_lock"}


# -- injected-race regression (static half) ---------------------------------

def test_injected_race_fails_closed_statically():
    """sim/bugs.py RacyCounterMinerNode carries reviewed waivers; with
    them stripped, conclint MUST flag the unlocked cross-root counter
    (rule rot guard — the runtime half lives in test_sim.py)."""
    src = (REPO / "arbius_tpu/sim/bugs.py").read_text()
    stripped = "\n".join(
        line for line in src.splitlines()
        if "detlint: allow[" not in line) + "\n"
    findings, _ = analyze_conc_sources({"arbius_tpu/sim/bugs.py": stripped})
    racy = [f for f in findings
            if f.rule == "CONC401" and "racy_counter" in f.message]
    assert racy, "stripping the waivers must expose the injected race"
    # with the checked-in waivers intact the tree stays clean (pinned
    # by the self-check above)
    findings, _ = analyze_conc_sources({"arbius_tpu/sim/bugs.py": src})
    assert not [f for f in findings
                if f.rule == "CONC401" and "racy_counter" in f.message]


# -- witness unit tests -----------------------------------------------------

def test_witness_lock_wrappers_record_roots_and_edges():
    import threading

    w = ConcWitness()
    w.register_root("tick")
    a = w.wrap_lock(threading.Lock(), "A")
    b = w.wrap_lock(threading.Lock(), "B")
    with a:
        with b:
            pass
    rep = w.report()
    assert {(e["lock"], e["root"]) for e in
            [{"lock": l["lock"], "root": l["root"]}
             for l in rep["locks"]]} == {("A", "tick"), ("B", "tick")}
    assert [(e["src"], e["dst"]) for e in rep["order_edges"]] == \
        [("A", "B")]
    assert order_cycle(rep) is None
    # reverse order on a "second thread" closes the cycle
    w.register_root("rpc")
    with b:
        with a:
            pass
    cycle = order_cycle(w.report())
    assert cycle is not None and cycle[0] == cycle[-1]


def test_witness_condition_wait_releases_hold():
    import threading

    w = ConcWitness()
    cv = w.wrap_lock(threading.Condition(), "CV")
    other = w.wrap_lock(threading.Lock(), "O")

    def waiter():
        with cv:
            cv.wait(timeout=0.01)
            # after wait returns the cv is re-held: an acquisition of
            # O now must record the CV→O edge
            with other:
                pass

    t = threading.Thread(target=waiter)
    t.start()
    t.join()
    edges = [(e["src"], e["dst"]) for e in w.report()["order_edges"]]
    assert ("CV", "O") in edges


def test_witness_watch_attrs_idempotent_and_restores(tmp_path):
    class Obj:
        pass

    w = ConcWitness()
    original = Obj.__setattr__
    w.watch_attrs(Obj, ("x",))
    w.watch_attrs(Obj, ("x",))   # crash-restart path: must not stack
    o = Obj()
    o.x = 1
    o.x = 2
    o.y = 3
    rep = w.report()
    assert sum(r["count"] for r in rep["attr_writes"]) == 2
    w.unwatch_all()
    assert Obj.__setattr__ is original


def test_witness_merge_reports():
    from arbius_tpu.analysis.conc.witness import merge_reports

    a = {"locks": [{"lock": "L", "root": "tick", "acquires": 2}],
         "order_edges": [{"src": "L", "dst": "M", "count": 1}],
         "attr_writes": []}
    b = {"locks": [{"lock": "L", "root": "tick", "acquires": 3},
                   {"lock": "M", "root": "rpc", "acquires": 1}],
         "order_edges": [{"src": "L", "dst": "M", "count": 4}],
         "attr_writes": [{"cls": "N", "attr": "x", "root": "tick",
                          "locks": [], "count": 1}]}
    m = merge_reports([a, b])
    assert m["locks"][0] == {"lock": "L", "root": "tick", "acquires": 5}
    assert m["order_edges"] == [{"src": "L", "dst": "M", "count": 5}]
    assert m["attr_writes"][0]["count"] == 1


def test_witness_crosscheck_and_annotation():
    report = {
        "order_edges": [],
        "attr_writes": [
            {"cls": "Node", "attr": "hot", "root": "tick",
             "locks": [], "count": 3},
            {"cls": "Node", "attr": "hot", "root": "rpc",
             "locks": [], "count": 1},
            {"cls": "Node", "attr": "cold", "root": "tick",
             "locks": [], "count": 5},
        ],
    }
    v = crosscheck([("Node", "hot"), ("Node", "cold"),
                    ("Node", "never")], report)
    assert v[("Node", "hot")] == "confirmed"
    assert v[("Node", "cold")] == "unwitnessed"
    assert v[("Node", "never")] == "unwitnessed"
    findings, _ = analyze_conc_sources(
        {"m.py": _THREADED % "threading.Thread(target=self._run)"})
    report2 = {
        "order_edges": [],
        "attr_writes": [
            {"cls": "Worker", "attr": "state", "root": "tick",
             "locks": [], "count": 1},
            {"cls": "Worker", "attr": "state", "root": "w",
             "locks": [], "count": 1},
        ],
    }
    annotated = annotate_findings(findings, report2)
    assert "[witness: confirmed]" in annotated[0].message
    # the baseline key (snippet) is untouched by annotation
    assert annotated[0].snippet == findings[0].snippet


# -- CLI --------------------------------------------------------------------

def test_cli_exit_codes(tmp_path, capsys):
    clean = tmp_path / "clean.py"
    clean.write_text("x = 1\n")
    racy = tmp_path / "racy.py"
    racy.write_text(_THREADED % "threading.Thread(target=self._run)")
    bl = str(tmp_path / "bl.json")
    assert cli_main([str(clean), "--root", str(tmp_path),
                     "--baseline", bl]) == 0
    assert cli_main([str(racy), "--root", str(tmp_path),
                     "--baseline", bl]) == 1
    assert cli_main([str(racy), "--select", "NOPE"]) == 2
    assert cli_main([str(tmp_path / "missing.py")]) == 2
    assert cli_main(["--help"]) == 0
    capsys.readouterr()


def test_cli_baseline_update_deterministic(tmp_path):
    racy = tmp_path / "racy.py"
    racy.write_text(_THREADED % "threading.Thread(target=self._run)")
    bl = tmp_path / "bl.json"
    args = [str(racy), "--root", str(tmp_path), "--baseline", str(bl),
            "--baseline-update"]
    assert cli_main(args) == 0
    doc = json.loads(bl.read_text())
    assert doc["findings"][0]["rule"] == "CONC401"
    doc["findings"][0]["reason"] = "reviewed: test fixture"
    bl.write_text(json.dumps(doc, indent=2, sort_keys=True) + "\n")
    first = bl.read_bytes()
    assert cli_main(args) == 0
    assert bl.read_bytes() == first
    assert cli_main([str(racy), "--root", str(tmp_path),
                     "--baseline", str(bl)]) == 0


def test_cli_select_runs_one_rule(tmp_path, capsys):
    src = _THREADED % "threading.Thread(target=self._run)"
    f = tmp_path / "f.py"
    f.write_text(src)
    rc = cli_main([str(f), "--root", str(tmp_path), "--json",
                   "--select", "CONC402",
                   "--baseline", str(tmp_path / "none.json")])
    assert rc == 0  # the race is CONC401; selecting 402 sees nothing
    capsys.readouterr()


def test_cli_witness_report_annotates(tmp_path, capsys):
    f = tmp_path / "f.py"
    f.write_text(_THREADED % "threading.Thread(target=self._run)")
    report = {
        "order_edges": [],
        "attr_writes": [
            {"cls": "Worker", "attr": "state", "root": "tick",
             "locks": [], "count": 1},
            {"cls": "Worker", "attr": "state", "root": "w",
             "locks": [], "count": 2},
        ],
    }
    wpath = tmp_path / "witness.json"
    wpath.write_text(json.dumps(report))
    rc = cli_main([str(f), "--root", str(tmp_path), "--json",
                   "--witness-report", str(wpath),
                   "--baseline", str(tmp_path / "none.json")])
    assert rc == 1
    doc = json.loads(capsys.readouterr().out)
    assert "[witness: confirmed]" in doc["findings"][0]["message"]


def test_tools_shell_and_module_entrypoint(tmp_path, capsys):
    import conclint as conclint_tool

    clean = tmp_path / "ok.py"
    clean.write_text("x = 1\n")
    assert conclint_tool.main([str(clean), "--root", str(tmp_path),
                               "--baseline",
                               str(tmp_path / "bl.json")]) == 0
    racy = tmp_path / "racy.py"
    racy.write_text(_THREADED % "threading.Thread(target=self._run)")
    assert conclint_tool.main([str(racy), "--root", str(tmp_path),
                               "--baseline",
                               str(tmp_path / "bl.json")]) == 1
    err = capsys.readouterr().err
    assert "findings by rule" in err and "CONC401" in err


@pytest.mark.slow
def test_module_entrypoint_runs_clean_on_tree():
    env = dict(os.environ, PYTHONPATH=str(REPO))
    out = subprocess.run(
        [sys.executable, "-m", "arbius_tpu.analysis.conc",
         str(REPO / "arbius_tpu"), "--root", str(REPO),
         "--baseline", str(REPO / "conclint-baseline.json")],
        capture_output=True, text=True, env=env, cwd=str(REPO))
    assert out.returncode == 0, out.stdout + out.stderr


# -- CONC406: cross-process sqlite discipline (docs/fleet.md) ---------------

def test_conc406_fixture_pair_fires_and_waives():
    """Path-scoped like CONC302: the fixture tree mirrors
    arbius_tpu/fleet/ so the rule sees a shared-db path."""
    findings, _, _ = analyze_conc_tree(
        [str(FIXDIR / "arbius_tpu")], root=str(FIXDIR))
    assert rules_of(findings) == ["CONC406", "CONC406"]
    assert all(f.path.endswith("conc406_pos.py") for f in findings)
    assert "busy_timeout" in findings[0].message
    assert "journal_mode=WAL" in findings[1].message


def test_conc406_out_of_scope_paths_are_ignored():
    src = "import sqlite3\n\ndef f(p):\n    return sqlite3.connect(p)\n"
    findings, _ = analyze_conc_sources({"tools/dumper.py": src})
    assert "CONC406" not in rules_of(findings)
    findings, _ = analyze_conc_sources(
        {"arbius_tpu/node/somedb.py": src})
    assert rules_of(findings) == ["CONC406"]
    # node-scoped handles need busy_timeout but NOT WAL (single file,
    # single process — only the fleet db is shared)
    ok = ("import sqlite3\n\ndef f(p):\n"
          "    c = sqlite3.connect(p)\n"
          "    c.execute('PRAGMA busy_timeout=5000')\n"
          "    return c\n")
    findings, _ = analyze_conc_sources({"arbius_tpu/node/somedb.py": ok})
    assert "CONC406" not in rules_of(findings)
    findings, _ = analyze_conc_sources({"arbius_tpu/fleet/somedb.py": ok})
    assert rules_of(findings) == ["CONC406"]
