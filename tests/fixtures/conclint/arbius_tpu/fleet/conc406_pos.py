"""CONC406 positives: fleet-path sqlite handles missing the
cross-process discipline — no busy_timeout at all, and a WAL-less
handle on the shared database."""
import sqlite3


def open_naked(path):
    return sqlite3.connect(path)           # CONC406: no busy_timeout


def open_half(path):
    conn = sqlite3.connect(path)           # CONC406: timeout but no WAL
    conn.execute("PRAGMA busy_timeout=5000")
    return conn
