"""CONC406 waived twin: the full discipline, plus a reasoned waiver."""
import sqlite3


def open_disciplined(path, busy_timeout_ms=5000):
    conn = sqlite3.connect(path)           # clean: both pragmas below
    conn.execute(f"PRAGMA busy_timeout={int(busy_timeout_ms)}")
    conn.execute("PRAGMA journal_mode=WAL")
    return conn


def open_scratch(path):
    # detlint: allow[CONC406] throwaway single-process scratch db for a
    # dump tool — nothing else ever opens this file
    return sqlite3.connect(path)
