"""CONC404 waived: teardown-only handle use."""
import sqlite3
import threading


class Closer:
    def __init__(self, path):
        self._conn = sqlite3.connect(path, check_same_thread=False)
        self._lock = threading.Lock()

    def write(self, v):
        with self._lock:
            self._conn.execute("INSERT INTO t VALUES (?)", (v,))

    def close(self):
        # detlint: allow[CONC404] teardown: callers stop every other
        # thread first; taking the lock here could deadlock a dying run
        self._conn.close()
