"""CONC405 waived: reviewed operator-surface write from a daemon."""
import sqlite3
import threading


class OpDB:
    def __init__(self, path):
        self._conn = sqlite3.connect(path)
        self._lock = threading.Lock()

    def enqueue(self, v):
        with self._lock:
            self._conn.execute("INSERT INTO jobs VALUES (?)", (v,))


class OperatorListener:
    def __init__(self, db):
        self.db = db
        self._t = threading.Thread(target=self._serve, daemon=True)

    def _serve(self):
        while True:
            # detlint: allow[CONC405] operator injection endpoint:
            # lock-guarded, fsynced before the caller is acked
            self.db.enqueue(1)


def build(path):
    return OperatorListener(OpDB(path))
