"""CONC403 waived + the wait() exemption."""
import threading
import time


class Batcher:
    def __init__(self):
        self._lock = threading.Lock()
        self._cv = threading.Condition()
        self.items = []

    def flush(self):
        with self._lock:
            # detlint: allow[CONC403] intentional: the lock exists to
            # serialize this one-shot settle; bounded at 50 ms
            time.sleep(0.05)

    def consume(self):
        with self._cv:
            while not self.items:
                self._cv.wait()    # releases the cv: NOT a finding
            return self.items.pop()
