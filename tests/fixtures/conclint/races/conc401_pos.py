"""CONC401 positive: attribute shared across roots, no common lock."""
import threading


class Miner:
    def __init__(self):
        self.status = "boot"
        self._lock = threading.Lock()
        self._t = threading.Thread(target=self._loop, daemon=True)

    def update(self, s):
        with self._lock:
            self.status = s        # writer holds the lock...

    def _loop(self):
        while self.status != "stop":   # ...the thread body does not
            pass
