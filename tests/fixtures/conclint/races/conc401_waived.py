"""CONC401 waived: same shape, reviewed and pragma'd."""
import threading


class Gauge:
    def __init__(self):
        self.reading = 0.0
        self._t = threading.Thread(target=self._sample, daemon=True)

    def publish(self, v):
        # detlint: allow[CONC401] cosmetic telemetry float: GIL-atomic
        # publish, sampler tolerates staleness
        self.reading = v

    def _sample(self):
        while True:
            print(self.reading)
