"""CONC405 positive: a daemon thread persisting state with no fence —
next to the fenced variant that must NOT fire."""
import sqlite3
import threading


class StateDB:
    def __init__(self, path):
        self._conn = sqlite3.connect(path)
        self._lock = threading.Lock()

    def save(self, v):
        with self._lock:
            self._conn.execute("UPDATE state SET v = ?", (v,))


class UnfencedNode:
    def __init__(self, db):
        self.db = db
        self._t = threading.Thread(target=self._flush, daemon=True)

    def _flush(self):
        while True:
            self.db.save(1)        # CONC405: daemon write, no fence


class FencedNode:
    def __init__(self, db):
        self.db = db
        self._gen = 0
        self._t = threading.Thread(target=self._flush, daemon=True)

    def tick(self):
        # detlint: allow[CONC401] monotonic int fence: GIL-atomic
        # publish; the daemon only ever compares it
        self._gen += 1

    def _flush(self):
        while True:
            if self._gen > 0:      # generation fence: main advances it
                self.db.save(self._gen)


def build(path):
    db = StateDB(path)
    return UnfencedNode(db), FencedNode(db)
