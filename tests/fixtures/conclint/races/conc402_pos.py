"""CONC402 positive: two locks acquired in both orders."""
import threading

ALPHA = threading.Lock()
BETA = threading.Lock()


def forward():
    with ALPHA:
        with BETA:
            pass


def backward():
    with BETA:
        with ALPHA:
            pass
