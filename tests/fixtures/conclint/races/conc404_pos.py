"""CONC404 positive: sqlite handle used off-lock — plus the
interprocedurally-proved-clean helper that must NOT fire."""
import sqlite3
import threading


class Store:
    def __init__(self, path):
        self._conn = sqlite3.connect(path, check_same_thread=False)
        self._lock = threading.Lock()

    def put(self, k, v):
        with self._lock:
            self._conn.execute("INSERT INTO kv VALUES (?, ?)", (k, v))
            self._commit()

    def _commit(self):
        self._conn.commit()        # clean: every caller holds _lock

    def peek(self, k):
        return self._conn.execute(   # CONC404: no lock on this path
            "SELECT v FROM kv WHERE k = ?", (k,)).fetchone()
