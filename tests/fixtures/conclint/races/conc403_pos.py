"""CONC403 positive: blocking calls while holding a lock — lexically
and through a call chain (the interprocedural half)."""
import threading
import time


class Pinner:
    def __init__(self):
        self._lock = threading.Lock()

    def direct(self):
        with self._lock:
            time.sleep(2.0)        # lexical

    def _slow_helper(self):
        time.sleep(1.0)            # held via every caller

    def indirect(self):
        with self._lock:
            self._slow_helper()
