"""CONC402 waived: a reviewed inversion (single-threaded tool code)."""
import threading

GAMMA = threading.Lock()
DELTA = threading.Lock()


def one_way():
    with GAMMA:
        # detlint: allow[CONC402] both paths run on the one CLI thread
        # — reviewed: no second thread ever takes these (the finding
        # anchors at the inversion's first acquisition site)
        with DELTA:
            pass


def other_way():
    with DELTA:
        with GAMMA:
            pass
