"""CONC301 fixture: the Timer and Thread-subclass spawn spellings the
rule was blind to before the conclint PR. Both classes share an
unlocked attribute with their thread body."""
import threading


class TimerRefresher:
    def __init__(self):
        self.stale = False
        self._t = threading.Timer(30.0, self._refresh)

    def mark(self):
        self.stale = True          # CONC301: races the timer thread

    def _refresh(self):
        if self.stale:
            self.stale = False


class SubclassWorker(threading.Thread):
    def __init__(self):
        super().__init__(daemon=True)
        self.command = None

    def send(self, cmd):
        self.command = cmd         # CONC301: races run()

    def run(self):
        while self.command != "stop":
            pass


class WaivedTimer:
    def __init__(self):
        self.label = ""
        self._t = threading.Timer(5.0, self._tick)

    def set_label(self, s):
        # detlint: allow[CONC301] cosmetic label, single writer, the
        # timer thread tolerates staleness
        self.label = s

    def _tick(self):
        print(self.label)
