"""OBS501 alert-direction fixture: catalog rule ids vs the doc.

Three `AlertRule(...)` constructors, each its own statement (a waiver
pragma attaches to its enclosing statement): one naming a rule the
REPO doc documents (clean — the forward direction checks the repo's
docs/observability.md, like the metric fixtures), one ghost with no
doc row (the finding the golden pins), and one waived. The sibling
docs/observability.md in THIS tree exercises the rot direction: it
documents one alert alive below and one whose name appears nowhere in
this tree.
"""
from arbius_tpu.obs.healthwatch import AlertRule


def catalog():
    # documented in the repo doc's alert table: clean
    documented = AlertRule(name="stuck_tick", summary="fixture",
                           signal="stuck")
    # no alert row anywhere: OBS501
    ghost = AlertRule(name="fixture_ghost_rule", summary="fixture",
                      signal="ghost")
    # detlint: allow[OBS501] fixture: a deliberate throwaway rule
    waived = AlertRule(name="fixture_waived_rule", summary="fixture",
                       signal="waived")
    return [documented, ghost, waived]
