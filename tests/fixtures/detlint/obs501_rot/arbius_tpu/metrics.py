"""Doc-rot fixture tree: one live literal, one f-string family.

The sibling docs/observability.md documents three names: the literal
registered below (alive), a member of the f-string family below (alive
via the family honesty bound), and a ghost whose name appears nowhere
in this tree — the rot the golden pins. (Any textual occurrence counts
as alive, so the ghost's name must not be spelled even here.)
"""


def register(reg):
    # detlint: allow[OBS501] fixture metric documented in the FIXTURE doc,
    # not the repo doc (this tree exercises the rot direction only)
    reg.counter("arbius_fixture_live_total", "still registered").inc()
    for name in ("a", "b"):
        reg.counter(f"arbius_fixture_roted_{name}_total", "family").inc()
