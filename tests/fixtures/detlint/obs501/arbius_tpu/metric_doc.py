"""OBS501 fixture: registered metric names vs docs/observability.md."""
from arbius_tpu.obs import current_obs


def report_documented():
    obs = current_obs()
    # documented rows: clean
    obs.registry.counter("arbius_tasks_seen_total").inc()
    obs.registry.histogram("arbius_stage_seconds",
                           labelnames=("stage",)).observe(1.0,
                                                          stage="infer")


def report_undocumented():
    obs = current_obs()
    # no row in docs/observability.md: OBS501, one per call site
    obs.registry.counter("arbius_fixture_rotting_total").inc()
    obs.registry.gauge(name="arbius_fixture_rotting_depth").set(1)


def report_waived():
    obs = current_obs()
    # detlint: allow[OBS501] fixture: a deliberate throwaway series
    obs.registry.counter("arbius_fixture_waived_total").inc()


def report_family():
    obs = current_obs()
    name = "tasks_seen"
    # family-constructor: non-literal names are out of OBS501's reach
    obs.registry.counter(f"arbius_{name}_total").inc()
