"""detlint golden fixture — one file, many findings across families.

tests/test_analysis.py analyzes this file and compares the JSON report
byte-for-byte against multi_finding.golden.json. Every construct below
is a deliberate violation; do not "fix" them.
"""
import glob
import json
import random
import time

import jax
import numpy as np


def stamp():
    return {"at": time.time(), "nonce": random.random()}


def scan(root):
    out = []
    for p in glob.glob(root + "/*.bin"):
        out.append(p)
    return out


def serialize(obj):
    return json.dumps(obj).encode()


@jax.jit
def bad_kernel(x):
    print("tracing", x)
    return np.asarray(x) + 1


def pick(items):
    for it in {"a", "b", "c"}:
        items.append(it)
    return items
