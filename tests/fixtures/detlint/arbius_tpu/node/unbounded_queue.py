"""detlint golden fixture — CONC302 unbounded-queue variants.

Lives under a fake `arbius_tpu/node/` prefix because CONC302 is scoped
to the miner's own stage buffers. Every bare construction below is a
deliberate violation; do not "fix" them.
"""
import queue
from queue import LifoQueue, Queue as Q

work = queue.Queue()                 # no maxsize: unbounded
alias = Q()                          # alias resolution must still catch it
lifo = LifoQueue(maxsize=0)          # stdlib 0 means infinite
prio = queue.PriorityQueue(maxsize=-1)   # negative is infinite too

bounded = queue.Queue(maxsize=8)     # fine: real backpressure
positional = queue.Queue(4)          # fine: positional bound
configured = queue.Queue(maxsize=max(1, 2))  # fine: non-literal bound
allowed = queue.Queue()  # detlint: allow[CONC302] drained same-tick, test rig
