"""Param checkpoint + compile-cache tests (SURVEY.md §5 checkpoint/resume)."""
from __future__ import annotations

import jax
import numpy as np

from arbius_tpu.utils import enable_compile_cache, load_params, save_params


def test_save_load_roundtrip(tmp_path):
    params = {"unet": {"conv": {"kernel": np.arange(12.0).reshape(3, 4),
                                "bias": np.zeros(4)}},
              "text": {"embed": np.ones((5, 2), np.float32)}}
    path = str(tmp_path / "ckpt")
    save_params(path, params)
    restored = load_params(path)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_array_equal(np.asarray(a),
                                                   np.asarray(b)),
        params, restored)


def test_save_overwrites(tmp_path):
    path = str(tmp_path / "ckpt")
    save_params(path, {"a": np.zeros(2)})
    save_params(path, {"a": np.ones(2)})
    np.testing.assert_array_equal(np.asarray(load_params(path)["a"]),
                                  np.ones(2))


def test_enable_compile_cache(tmp_path):
    cache = str(tmp_path / "xla")
    enable_compile_cache(cache)
    import os
    assert os.path.isdir(cache)
    # config took effect (idempotent re-set is fine too)
    assert jax.config.jax_compilation_cache_dir == cache
    enable_compile_cache(cache)
