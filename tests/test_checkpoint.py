"""Param checkpoint + compile-cache tests (SURVEY.md §5 checkpoint/resume)."""
from __future__ import annotations

import jax
import numpy as np

from arbius_tpu.utils import enable_compile_cache, load_params, save_params


def test_save_load_roundtrip(tmp_path):
    params = {"unet": {"conv": {"kernel": np.arange(12.0).reshape(3, 4),
                                "bias": np.zeros(4)}},
              "text": {"embed": np.ones((5, 2), np.float32)}}
    path = str(tmp_path / "ckpt")
    save_params(path, params)
    restored = load_params(path)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_array_equal(np.asarray(a),
                                                   np.asarray(b)),
        params, restored)


def test_save_overwrites(tmp_path):
    path = str(tmp_path / "ckpt")
    save_params(path, {"a": np.zeros(2)})
    save_params(path, {"a": np.ones(2)})
    np.testing.assert_array_equal(np.asarray(load_params(path)["a"]),
                                  np.ones(2))


def test_enable_compile_cache(tmp_path):
    cache = str(tmp_path / "xla")
    enable_compile_cache(cache)
    import os
    assert os.path.isdir(cache)
    # config took effect (idempotent re-set is fine too)
    assert jax.config.jax_compilation_cache_dir == cache
    enable_compile_cache(cache)


def test_fused_init_cast_matches_separate_cast():
    """init_params(dtype=) must be bit-identical to init-then-cast.

    The fused form exists for HBM peak (a separate cast program holds the
    f32 AND bf16 trees live at once — it OOMed the ~3B kandinsky tree on
    a 16 GB chip), but goldens were recorded via the two-program path, so
    the bits must not move. Covers every pipeline family's init path.
    """
    import jax.numpy as jnp

    from arbius_tpu.models.kandinsky2 import Kandinsky2Config, Kandinsky2Pipeline
    from arbius_tpu.models.rvm import RVMPipeline, RVMPipelineConfig
    from arbius_tpu.models.sd15 import SD15Config, SD15Pipeline
    from arbius_tpu.models.video import Text2VideoConfig, Text2VideoPipeline
    from arbius_tpu.utils import cast_floating

    pipes = [
        SD15Pipeline(SD15Config.tiny()),
        Kandinsky2Pipeline(Kandinsky2Config.tiny()),
        Text2VideoPipeline(Text2VideoConfig.tiny()),
        RVMPipeline(RVMPipelineConfig.tiny()),
    ]
    for pipe in pipes:
        ref = jax.jit(lambda p: cast_floating(p, "bfloat16"))(
            pipe.init_params(seed=0))
        fused = pipe.init_params(seed=0, dtype="bfloat16")
        leaves_ref = jax.tree_util.tree_leaves_with_path(ref)
        leaves_fused = jax.tree_util.tree_leaves_with_path(fused)
        assert len(leaves_ref) == len(leaves_fused)
        for (path_r, a), (path_f, b) in zip(leaves_ref, leaves_fused):
            assert path_r == path_f
            assert a.dtype == b.dtype, (type(pipe).__name__, path_r)
            if jnp.issubdtype(a.dtype, jnp.inexact):
                assert a.dtype == jnp.bfloat16, (type(pipe).__name__, path_r)
            np.testing.assert_array_equal(
                np.asarray(a, np.float32), np.asarray(b, np.float32),
                err_msg=f"{type(pipe).__name__} {path_r}")
