"""perfscope (docs/perfscope.md) acceptance suite.

The non-negotiable is determinism: CIDs must be byte-identical
perfscope-on vs off — pinned here for the image probe (mesh-off AND
dp2), the video-shaped seq probe, a real tiny SD-1.5 through
solve_cid_batch, and a full simnet clean scenario. Around that: card
capture (XLA cost/memory facts, padding, drift band, persistence,
aotcache header amortization), the byte-deterministic Chrome-trace
export, and the PERF601 auditor's fail-closed behavior on a mispriced
bucket.
"""
from __future__ import annotations

import json
import os
import sys

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURES = os.path.join(REPO, "tests", "fixtures", "perfscope")


def _scoped_obs(**scope_kw):
    from arbius_tpu.obs import Obs
    from arbius_tpu.obs.perfscope import PerfScope

    obs = Obs(journal_capacity=256)
    obs.perfscope = PerfScope(obs, **scope_kw)
    return obs


# -- CID byte-equality: perfscope on vs off ---------------------------------

def _probe_bytes(probe_cls, scope_on, mesh=None, **probe_kw):
    from arbius_tpu.obs import Obs, use_obs

    obs = _scoped_obs() if scope_on else Obs(journal_capacity=64)
    probe = probe_cls(mesh=mesh, **probe_kw)
    items = [({"prompt": "perf x"}, 7), ({"prompt": "perf y"}, 8)]
    with use_obs(obs):
        out = np.asarray(probe.dispatch(items)).tobytes()
        np.asarray(probe.dispatch(items))  # memory-tier hit
    return out, obs


def test_image_probe_cids_identical_scope_on_off_and_dp2():
    from arbius_tpu.parallel import meshsolve
    from arbius_tpu.parallel.meshsolve import ShardedImageProbe

    off, _ = _probe_bytes(ShardedImageProbe, False)
    on, obs = _probe_bytes(ShardedImageProbe, True)
    assert off == on
    # the card captured at the compile seam, with real XLA statics
    (card,) = obs.perfscope.cards()
    assert card.tag == "meshprobe.img.b2"
    assert card.flops > 0 and card.bytes_accessed > 0
    assert card.compile_seconds > 0 and card.source == "compiled"
    assert card.roofline_s > 0
    # dp2: sharded program, wire bytes land on the card
    mesh = meshsolve.boot_mesh({"dp": 2})
    off2, _ = _probe_bytes(ShardedImageProbe, False, mesh=mesh)
    on2, obs2 = _probe_bytes(ShardedImageProbe, True, mesh=mesh)
    assert off2 == on2
    (card2,) = obs2.perfscope.cards()
    assert card2.wire_bytes.get("dp", 0) > 0


def test_seq_probe_cids_identical_scope_on_off():
    from arbius_tpu.parallel.meshsolve import ShardedSeqProbe

    off, _ = _probe_bytes(ShardedSeqProbe, False)
    on, obs = _probe_bytes(ShardedSeqProbe, True)
    assert off == on
    (card,) = obs.perfscope.cards()
    assert card.tag.startswith("meshprobe.seq.") and card.flops > 0


def test_sd15_cids_identical_scope_on_off():
    """A real (tiny) SD-1.5 solve through solve_cid_batch: perfscope
    off vs on must emit byte-identical (cid, files)."""
    from arbius_tpu.models.sd15 import SD15Config, SD15Pipeline
    from arbius_tpu.node.factory import tiny_byte_tokenizer
    from arbius_tpu.node.solver import (
        ModelRegistry,
        RegisteredModel,
        SD15Runner,
        solve_cid_batch,
    )
    from arbius_tpu.obs import Obs, use_obs
    from arbius_tpu.templates.engine import load_template

    cfg = SD15Config.tiny()
    params = SD15Pipeline(
        cfg, tokenizer=tiny_byte_tokenizer(cfg.text)).init_params(
        seed=0, height=64, width=64)
    tmpl = load_template("anythingv3")
    items = [({"prompt": "perf cat", "negative_prompt": "", "width": 64,
               "height": 64, "num_inference_steps": 2,
               "scheduler": "DDIM", "seed": 7}, 7)]

    def life(scope_on: bool):
        pipe = SD15Pipeline(cfg, tokenizer=tiny_byte_tokenizer(cfg.text))
        model = RegisteredModel(id="0x" + "11" * 32, template=tmpl,
                                runner=SD15Runner(pipe, params))
        ModelRegistry().register(model)
        obs = _scoped_obs() if scope_on else Obs(journal_capacity=64)
        with use_obs(obs):
            out = solve_cid_batch(model, items, canonical_batch=1)
        return out, obs

    off, _ = life(False)
    on, obs = life(True)
    assert off == on  # (cid, files) pairs, bytes and all
    (card,) = obs.perfscope.cards()
    assert card.tag.startswith("sd15.") and card.flops > 0
    assert card.arg_bytes > 0 and card.out_bytes > 0


def test_sim_clean_scenario_cids_identical_scope_on_off(tmp_path):
    """Cards must not perturb CIDs through the whole signed-tx node
    path: a clean simnet run perfscope-on matches perfscope-off."""
    from arbius_tpu.sim.harness import run_scenario
    from arbius_tpu.sim.invariants import check_all
    from arbius_tpu.sim.scenario import get_scenario

    def cids(r):
        return {"0x" + t.hex(): "0x" + s.cid.hex()
                for t, s in r.engine.solutions.items()}

    base = run_scenario(get_scenario("clean"), 1, mesh={})
    scoped = run_scenario(get_scenario("clean"), 1, mesh={},
                          perfscope=True)
    for r in (base, scoped):
        findings = check_all(r)
        assert not findings, [f.text() for f in findings]
    assert cids(base) == cids(scoped) and cids(base)


# -- capture / bind / drift --------------------------------------------------

def _captured_scope(**scope_kw):
    """One image-probe dispatch under a fresh scoped obs → (scope, tag)."""
    from arbius_tpu.parallel.meshsolve import ShardedImageProbe

    _, obs = _probe_bytes(ShardedImageProbe, False)  # warm numpy etc.
    obs = _scoped_obs(**scope_kw)
    from arbius_tpu.obs import use_obs

    probe = ShardedImageProbe()
    with use_obs(obs):
        probe.dispatch([({"prompt": "a"}, 1), ({"prompt": "b"}, 2)])
    return obs, "meshprobe.img.b2"


def test_observe_dispatch_binds_accrues_and_journals_drift_on_crossing():
    obs, tag = _captured_scope(drift_min=0.5, drift_max=2.0)
    scope = obs.perfscope
    card = scope.cards()[0]
    roof = card.roofline_s

    def disp(bucket_wall):
        # a 3-real-task bucket at canonical batch 2 = 2 executable
        # dispatches (one padded slot); the observed window stores the
        # PER-DISPATCH wall, so drift is queue-depth-invariant
        return scope.observe_dispatch(
            tag, model="0xmm", bucket="64x64.s2.DDIM.f-",
            layout="single", mode="bf16", batch=2, real=3, padded=1,
            dispatches=2, seconds=bucket_wall)

    assert disp(roof * 2 * 1.0) == pytest.approx(1.0)
    assert obs.journal.events(kind="perf_drift") == []
    # crossing out of band journals ONCE; staying out journals nothing
    # (upper-middle window median: p50 of [1x, 9x] is 9x)
    assert disp(roof * 2 * 9.0) == pytest.approx(9.0)
    disp(roof * 2 * 9.0)
    drifts = obs.journal.events(kind="perf_drift")
    assert len(drifts) == 1
    assert drifts[0]["model"] == "0xmm" and \
        drifts[0]["band"] == [0.5, 2.0]
    card = scope.cards()[0]
    assert card.bound and card.mode == "bf16"
    assert card.dispatches == 6 and card.real_tasks == 9
    assert card.padded_slots == 3
    assert card.padding_waste() == pytest.approx(0.25)
    # the live gauge serves the same ratio, per cost key
    g = obs.registry.get("arbius_perf_drift_ratio")
    val = g.value(model="0xmm", bucket="64x64.s2.DDIM.f-",
                  layout="single", mode="bf16")
    assert val == pytest.approx(card.drift_ratio())
    assert obs.registry.get("arbius_perf_cards").value() == 1.0


def test_dirty_rows_persist_and_reload_through_nodedb(tmp_path):
    from arbius_tpu.node.db import NodeDB

    obs, tag = _captured_scope()
    scope = obs.perfscope
    # unbound cards never persist
    assert scope.dirty_rows(5) == []
    scope.observe_dispatch(tag, model="0xmm", bucket="b", layout="single",
                           mode="bf16", batch=2, real=2, padded=0,
                           seconds=0.5)
    rows = scope.dirty_rows(7)
    assert len(rows) == 1 and rows[0][:4] == ("0xmm", "b", "single",
                                              "bf16")
    assert scope.dirty_rows(8) == []  # drained
    db = NodeDB(str(tmp_path / "n.sqlite"))
    try:
        db.upsert_perf_cards(rows)
        loaded = db.load_perf_cards()
    finally:
        db.close()
    ((model, bucket, layout, mode, card, updated),) = loaded
    assert (model, bucket, layout, mode, updated) == \
        ("0xmm", "b", "single", "bf16", 7)
    assert card["flops"] > 0 and card["observed_p50_seconds"] == 0.5


def test_capture_failure_degrades_to_lazy_path():
    """A broken aot_args thunk must fall back to the exact pre-perfscope
    contract: lazy callable, warm=False, skip counted + journaled."""
    from arbius_tpu.obs import jit_cache_get, use_obs

    obs = _scoped_obs()
    cache: dict = {}
    built = []

    def build():
        built.append(1)
        return lambda x: x + 1  # not jittable via .lower — irrelevant

    def bad_args():
        raise RuntimeError("no operands today")

    with use_obs(obs):
        fn, warm, tag = jit_cache_get(cache, "k", build, tag="t.b1",
                                      aot_args=bad_args)
    assert warm is False and built == [1] and cache["k"] is fn
    assert fn(1) == 2
    assert obs.registry.counter(
        "arbius_perf_capture_skips_total").value() == 1
    assert obs.journal.events(kind="perf_capture_skip")


def test_aot_header_perf_block_and_disk_amortization(tmp_path):
    """Cold life publishes the card's perf block into the entry header;
    a warm life's disk-hit card adopts the ORIGINAL compile cost
    (source=disk) — the cross-life amortization seam."""
    from arbius_tpu.aotcache import AotCache, read_header, scan
    from arbius_tpu.obs import use_obs
    from arbius_tpu.parallel.meshsolve import ShardedImageProbe

    d = str(tmp_path / "cache")
    items = [({"prompt": "amort"}, 3), ({"prompt": "izer"}, 4)]

    def life():
        obs = _scoped_obs()
        obs.aot_cache = AotCache(d)
        with use_obs(obs):
            ShardedImageProbe().dispatch(items)
        return obs

    cold = life()
    (cold_card,) = cold.perfscope.cards()
    assert cold_card.source == "compiled" and \
        cold_card.compile_seconds > 0
    ((_, path, _),) = scan(d)
    perf = read_header(path)["perf"]
    assert perf["flops"] == cold_card.flops
    assert perf["compile_seconds"] == pytest.approx(
        cold_card.compile_seconds, abs=1e-6)
    warm = life()
    (warm_card,) = warm.perfscope.cards()
    assert warm.registry.counter("arbius_aot_cache_loads_total"
                                 ).value() == 1
    assert warm_card.source == "disk"
    assert warm_card.compile_seconds == perf["compile_seconds"]
    assert warm_card.flops == cold_card.flops


# -- chrome trace ------------------------------------------------------------

def _fixture_events():
    with open(os.path.join(FIXTURES, "journal.json")) as f:
        return json.load(f)["events"]


def test_chrome_trace_golden_bytes_and_schema():
    from arbius_tpu.obs.perfscope import chrome_trace, render_chrome_trace

    events = _fixture_events()
    got = render_chrome_trace(events)
    with open(os.path.join(FIXTURES, "trace.golden.json")) as f:
        assert got == f.read()
    doc = json.loads(got)
    assert doc["displayTimeUnit"] == "ms"
    names = set()
    for ev in doc["traceEvents"]:
        assert ev["ph"] in ("M", "X", "i")
        assert isinstance(ev["pid"], int) and isinstance(ev["tid"], int)
        assert "name" in ev
        if ev["ph"] == "X":
            assert ev["dur"] >= 1 and ev["ts"] >= 0
        names.add(ev["name"])
    # one process row per member; lifecycle instants ride task tracks
    members = {e["args"]["name"] for e in doc["traceEvents"]
               if e["ph"] == "M"}
    assert members == {"coord", "w1", "w2"}
    assert {"solve.batch", "lease_hop", "gate_decision",
            "perf_drift"} <= names
    w2 = next(e["pid"] for e in doc["traceEvents"]
              if e["ph"] == "M" and e["args"]["name"] == "w2")
    stage = [e for e in doc["traceEvents"] if e["ph"] == "i"
             and e["pid"] == w2 and e["name"] == "pipeline_stage"]
    root = [e for e in doc["traceEvents"] if e["ph"] == "X"
            and e["pid"] == w2 and e["name"] == "solve.batch"]
    assert stage and root and stage[0]["tid"] == root[0]["tid"]
    # pure: same events, same bytes
    assert render_chrome_trace(list(events)) == got
    assert chrome_trace([]) == {"displayTimeUnit": "ms",
                                "traceEvents": []}


def _tool(argv, capsys):
    sys.path.insert(0, os.path.join(REPO, "tools"))
    try:
        import perfscope as tool
    finally:
        sys.path.pop(0)
    rc = tool.main(argv)
    out = capsys.readouterr()
    return rc, out.out, out.err


def test_chrome_trace_cli_matches_golden(capsys):
    rc, out, _ = _tool(
        ["--chrome-trace", os.path.join(FIXTURES, "journal.json")],
        capsys)
    assert rc == 0
    with open(os.path.join(FIXTURES, "trace.golden.json")) as f:
        assert out == f.read()


def test_chrome_trace_cli_usage_errors(capsys):
    rc, _, err = _tool(["--chrome-trace"], capsys)
    assert rc == 2 and "--fleet" in err
    rc, _, err = _tool([], capsys)
    assert rc == 2 and "--db" in err


# -- PERF601 auditor ---------------------------------------------------------

def _audit_db(tmp_path, chip_seconds: float, card_overrides=None):
    """A node db with one bound card + one fitted cost row joined on
    the shared (model, bucket, layout, mode) tag."""
    from arbius_tpu.node.db import NodeDB

    card = {"tag": "sd15.2.64.64.2.DDIM", "model": "0xmm", "bucket": "b",
            "layout": "single", "mode": "bf16", "batch": 2,
            "flops": 1e9, "bytes_accessed": 1e8, "arg_bytes": 10,
            "out_bytes": 10, "temp_bytes": 0, "code_bytes": 0,
            "compile_seconds": 0.5, "source": "compiled",
            "roofline_seconds": 0.001, "dispatches": 4, "real_tasks": 8,
            "padded_slots": 0, "padding_waste": 0.0,
            "amortized_compile_seconds": 0.125, "wire_bytes": {},
            "drift_ratio": 1.0, "observed_p50_seconds": 0.001}
    card.update(card_overrides or {})
    path = str(tmp_path / "audit.sqlite")
    db = NodeDB(path)
    try:
        db.upsert_perf_cards([("0xmm", "b", "single", "bf16",
                               json.dumps(card, sort_keys=True), 9)])
        db.upsert_cost_rows([("0xmm", "b", "single", "bf16",
                              chip_seconds, 16, 9)])
    finally:
        db.close()
    return path


def test_perf601_clean_and_fail_closed(tmp_path, capsys):
    # consistent: fitted 2 × 0.0005 s/task = 0.001 s bucket = roofline
    clean = _audit_db(tmp_path, 0.0005)
    rc, out, _ = _tool(["--db", clean], capsys)
    assert rc == 0 and "within the drift band" in out
    # mispriced: the fitted row claims 100× the roofline — PERF601,
    # exit 1, even though the card's own observed window looked fine
    (tmp_path / "m").mkdir()
    bad = _audit_db(tmp_path / "m", 0.05)
    rc, out, _ = _tool(["--db", bad], capsys)
    assert rc == 1 and "PERF601" in out and "fitted-row" in out
    # observed-window drift fails too
    (tmp_path / "w").mkdir()
    wobbly = _audit_db(tmp_path / "w", 0.0005,
                       card_overrides={"drift_ratio": 7.5})
    rc, out, _ = _tool(["--db", wobbly], capsys)
    assert rc == 1 and "observed-window" in out
    # a widened band absolves it; --json is the standard document
    rc, out, _ = _tool(["--db", wobbly, "--drift-max", "10"], capsys)
    assert rc == 0
    rc, out, _ = _tool(["--db", bad, "--json"], capsys)
    assert rc == 1
    doc = json.loads(out)
    assert doc["findings"][0]["rule"] == "PERF601"
    assert doc["findings"][0]["snippet"] == "0xmm|b|single|bf16"


def test_costmodel_dump_joins_cards(tmp_path, capsys):
    """tools/costmodel.py --dump grows the perf columns when the db has
    cards, and renders the historic table byte-for-byte when not."""
    sys.path.insert(0, os.path.join(REPO, "tools"))
    try:
        import costmodel as cm_tool
    finally:
        sys.path.pop(0)
    from arbius_tpu.node.db import NodeDB

    bare = str(tmp_path / "bare.sqlite")
    db = NodeDB(bare)
    db.upsert_cost_rows([("0xmm", "b", "single", "bf16", 0.25, 16, 9)])
    db.close()
    rows = cm_tool.load_db_rows(bare)
    assert "flops" not in rows[0]
    out = cm_tool.render_rows(rows)
    assert "flops" not in out and "chip_seconds" in out
    joined = _audit_db(tmp_path, 0.0005)
    rows = cm_tool.load_db_rows(joined)
    assert rows[0]["flops"] == 1e9
    assert rows[0]["utilization"] == 1.0  # roofline == fitted bucket wall
    table = cm_tool.render_rows(rows)
    assert "flops" in table and "utilization" in table


# -- node integration --------------------------------------------------------

def _mini_node(tmp_path, *, perfscope=True, drift_max=0.0):
    from arbius_tpu.chain import WAD, Engine, TokenLedger
    from arbius_tpu.node import (
        LocalChain,
        MinerNode,
        MiningConfig,
        ModelConfig,
        ModelRegistry,
        RegisteredModel,
    )
    from arbius_tpu.node.config import PerfscopeConfig
    from arbius_tpu.parallel.meshsolve import ShardedImageProbe
    from arbius_tpu.templates.engine import load_template

    tok = TokenLedger()
    eng = Engine(tok, start_time=10_000)
    tok.mint(Engine.ADDRESS, 600_000 * WAD)
    miner, user = "0x" + "aa" * 20, "0x" + "01" * 20
    for a in (miner, user):
        tok.mint(a, 10**6 * WAD)
        tok.approve(a, Engine.ADDRESS, 10**30)
    mid = "0x" + eng.register_model(user, user, 0, b"{}").hex()
    registry = ModelRegistry()
    registry.register(RegisteredModel(
        id=mid, template=load_template("anythingv3"),
        runner=ShardedImageProbe()))
    chain = LocalChain(eng, miner)
    chain.validator_deposit(100 * WAD)
    node = MinerNode(
        chain,
        MiningConfig(models=(ModelConfig(id=mid, template="anythingv3"),),
                     db_path=str(tmp_path / "node.sqlite"),
                     canonical_batch=2, compile_cache_dir=None,
                     perfscope=PerfscopeConfig(enabled=perfscope,
                                               drift_max=drift_max)),
        registry)
    node.boot(skip_self_test=True)
    return node, eng, user, mid


def test_node_binds_cards_and_persists_in_tick_window(tmp_path):
    node, eng, user, mid = _mini_node(tmp_path)
    try:
        # 3 tasks at canonical_batch 2 → 2 chunks, 1 padded slot
        for i in range(3):
            eng.submit_task(user, 0, user, bytes.fromhex(mid[2:]), 0,
                            json.dumps({"prompt": f"p{i}",
                                        "negative_prompt": ""},
                                       sort_keys=True).encode())
        for _ in range(64):
            if node.tick() == 0:
                break
        assert len(eng.solutions) == 3
        scope = node.obs.perfscope
        (card,) = scope.cards()
        assert card.bound and card.model == mid
        assert card.layout == "single" and card.mode == "bf16"
        assert card.batch == 2
        assert card.real_tasks == 3 and card.padded_slots == 1
        assert card.flops > 0
        rows = node.db.load_perf_cards()
        assert len(rows) == 1 and rows[0][0] == mid
        # the persisted card is the live card's JSON
        assert rows[0][4]["padding_waste"] == pytest.approx(0.25)
    finally:
        node.close()


def test_debug_costmodel_view_joins_perf(tmp_path):
    from arbius_tpu.node.rpc import ControlRPC

    node, eng, user, mid = _mini_node(tmp_path)
    try:
        for i in range(4):
            eng.submit_task(user, 0, user, bytes.fromhex(mid[2:]), 0,
                            json.dumps({"prompt": f"q{i}",
                                        "negative_prompt": ""},
                                       sort_keys=True).encode())
            for _ in range(64):
                if node.tick() == 0:
                    break
        # accrue enough samples for a fitted row, then refit
        node._ingest_costs()
        rpc = ControlRPC.__new__(ControlRPC)
        rpc.node = node
        code, doc = rpc.debug_view("/debug/costmodel")
        assert code == 200
        assert doc["perfscope"]["cards"]
        rows = doc["cost_model"]["rows"]
        assert rows, "no fitted rows accrued"
        perf = rows[0].get("perf")
        assert perf and perf["flops"] > 0
        assert "roofline_seconds" in perf and "utilization" in perf
    finally:
        node.close()


def test_debug_trace_inlines_lifecycle_events_in_seq_order():
    """/debug/trace returns the task's non-span journal events inline,
    ordered — gate/cost decisions and pipeline stages in one view."""
    from arbius_tpu.node.rpc import ControlRPC
    from arbius_tpu.obs import Obs

    obs = Obs(journal_capacity=64)
    obs.event("gate_decision", taskid="0xt", verdict="accept")
    with obs.span("solve.batch", taskids=["0xt"]):
        pass
    obs.event("pipeline_stage", taskid="0xt", stage="solve", rank=0)
    obs.event("pipeline_stage", taskid="0xother", stage="solve", rank=0)
    obs.event("pipeline_stage", taskid="0xt", stage="encode", rank=1)
    obs.event("pipeline_stage", taskid="0xt", stage="reveal", rank=4)

    class _Stub:
        pass

    node = _Stub()
    node.obs = obs
    rpc = ControlRPC.__new__(ControlRPC)
    rpc.node = node
    code, doc = rpc.debug_view("/debug/trace?taskid=0xt")
    assert code == 200
    assert doc["spans"], "span trees still served"
    kinds = [(e["kind"], e.get("stage")) for e in doc["events"]]
    assert kinds == [("gate_decision", None), ("pipeline_stage", "solve"),
                     ("pipeline_stage", "encode"),
                     ("pipeline_stage", "reveal")]
    seqs = [e["seq"] for e in doc["events"]]
    assert seqs == sorted(seqs)
    assert all(e.get("taskid") == "0xt" for e in doc["events"])


def test_perfscope_config_validation():
    from arbius_tpu.node.config import ConfigError, load_config

    with pytest.raises(ConfigError):
        load_config('{"perfscope": {"drift_min": -1}}')
    with pytest.raises(ConfigError):
        load_config('{"perfscope": {"drift_min": 2.0, "drift_max": 1.0}}')
    with pytest.raises(ConfigError):
        load_config('{"perfscope": {"peak_flops": -5}}')
    with pytest.raises(ConfigError):
        load_config('{"perfscope": {"nope": 1}}')
    cfg = load_config('{"perfscope": {"enabled": true, '
                      '"drift_min": 0.5, "drift_max": 2.0}}')
    assert cfg.perfscope.enabled and cfg.perfscope.drift_max == 2.0
    with open(os.path.join(REPO, "MiningConfig.example.json")) as f:
        example = load_config(f.read())
    assert example.perfscope.enabled is False
