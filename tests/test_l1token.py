"""Bridge-pair tests: L1Token + custom gateway escrow ↔ L2 TokenLedger.

Mirrors the L1 half the round-2 verdict flagged missing
(`contract/contracts/L1Token.sol:34-60`): premined supply, the
isArbitrumEnabled/0xb1 registration latch, owner gating, and exact
round-trip of bridged amounts through the gateway escrow into the L2
token's gateway-gated mint/burn (`BaseTokenV1.sol:54-68`).
"""
import pytest

from arbius_tpu.chain import (
    L1CustomGateway,
    L1Token,
    L2GatewayRouter,
    TokenLedger,
)
from arbius_tpu.chain.fixedpoint import WAD
from arbius_tpu.chain.l1token import ARBITRUM_ENABLED_MAGIC

DEPLOYER = "0x" + "d0" * 20
ALICE = "0x" + "a1" * 20
BOB = "0x" + "b0" * 20
L2_ADDR = "0x" + "22" * 20


def build_bridge(initial=1_000_000):
    gw = L1CustomGateway()
    router = L2GatewayRouter()
    l1 = L1Token(DEPLOYER, gw, router, initial)
    l2 = TokenLedger()
    l1.register_token_on_l2(DEPLOYER, L2_ADDR)
    gw.connect_l2(l1, l2)
    return l1, l2, gw, router


def test_premint_goes_to_deployer():
    l1, _, _, _ = build_bridge(initial=1_000_000)
    assert l1.balance_of(DEPLOYER) == 1_000_000 * WAD
    assert l1.total_supply == 1_000_000 * WAD


def test_registration_is_owner_only():
    gw, router = L1CustomGateway(), L2GatewayRouter()
    l1 = L1Token(DEPLOYER, gw, router, 10)
    with pytest.raises(ValueError, match="not the owner"):
        l1.register_token_on_l2(ALICE, L2_ADDR)


def test_is_arbitrum_enabled_latch():
    """0xb1 only answers during registerTokenOnL2 (L1Token.sol:55-58) —
    outside the latch the probe reverts, and the latch is restored after."""
    gw, router = L1CustomGateway(), L2GatewayRouter()
    l1 = L1Token(DEPLOYER, gw, router, 10)
    with pytest.raises(ValueError, match="NOT_EXPECTED_CALL"):
        l1.is_arbitrum_enabled()
    seen = []
    orig = gw.register_token_to_l2
    gw.register_token_to_l2 = lambda tok, addr: (
        seen.append(tok.is_arbitrum_enabled()), orig(tok, addr))
    l1.register_token_on_l2(DEPLOYER, L2_ADDR)
    assert seen == [ARBITRUM_ENABLED_MAGIC]
    with pytest.raises(ValueError, match="NOT_EXPECTED_CALL"):
        l1.is_arbitrum_enabled()


def test_deposit_escrows_and_mints_on_l2():
    l1, l2, gw, _ = build_bridge()
    l1.transfer(DEPLOYER, ALICE, 100 * WAD)
    l1.approve(ALICE, gw.ADDRESS, 100 * WAD)
    gw.outbound_transfer(l1, ALICE, ALICE, 60 * WAD)
    assert l1.balance_of(ALICE) == 40 * WAD
    assert gw.escrowed(l1) == 60 * WAD
    assert l2.balance_of(ALICE) == 60 * WAD
    assert l2.total_supply == 60 * WAD


def test_deposit_requires_approval():
    l1, _, gw, _ = build_bridge()
    l1.transfer(DEPLOYER, ALICE, 10 * WAD)
    with pytest.raises(ValueError, match="insufficient allowance"):
        gw.outbound_transfer(l1, ALICE, ALICE, 10 * WAD)


def test_withdraw_burns_and_releases_escrow():
    l1, l2, gw, _ = build_bridge()
    l1.transfer(DEPLOYER, ALICE, 100 * WAD)
    l1.approve(ALICE, gw.ADDRESS, 100 * WAD)
    gw.outbound_transfer(l1, ALICE, ALICE, 100 * WAD)
    gw.finalize_inbound_transfer(l1, ALICE, BOB, 30 * WAD)
    assert l2.balance_of(ALICE) == 70 * WAD
    assert l2.total_supply == 70 * WAD
    assert l1.balance_of(BOB) == 30 * WAD
    assert gw.escrowed(l1) == 70 * WAD


def test_l2_mint_rejects_non_gateway_sender():
    _, l2, _, _ = build_bridge()
    with pytest.raises(ValueError, match="NOT_GATEWAY"):
        l2.bridge_mint(ALICE, ALICE, WAD)


def test_deposit_rolls_back_escrow_when_l2_cap_reverts():
    """A max-supply revert on L2 must not strand the deposit in escrow —
    the Solidity pair is atomic per tx."""
    l1, l2, gw, _ = build_bridge()
    l2.mint("0x" + "ee" * 20, 999_950 * WAD)  # engine emissions on L2
    l1.transfer(DEPLOYER, ALICE, 100 * WAD)
    l1.approve(ALICE, gw.ADDRESS, 100 * WAD)
    with pytest.raises(ValueError, match="max supply"):
        gw.outbound_transfer(l1, ALICE, ALICE, 100 * WAD)
    assert l1.balance_of(ALICE) == 100 * WAD
    assert gw.escrowed(l1) == 0
    assert l2.balance_of(ALICE) == 0


def test_withdraw_of_unescrowed_l2_mint_refused_before_burn():
    """L2-native mining emissions aren't escrow-backed; withdrawing them
    must refuse up front, not burn and then fail the L1 release."""
    l1, l2, gw, _ = build_bridge()
    l2.gateway = gw.ADDRESS
    l2.bridge_mint(gw.ADDRESS, ALICE, 0)  # keep gateway wiring exercised
    l2.mint(ALICE, 50 * WAD)  # mined on L2, never deposited
    with pytest.raises(ValueError, match="escrow insufficient"):
        gw.finalize_inbound_transfer(l1, ALICE, ALICE, 50 * WAD)
    assert l2.balance_of(ALICE) == 50 * WAD
    assert l2.total_supply == 50 * WAD


def test_withdraw_more_than_l2_balance_fails():
    l1, _, gw, _ = build_bridge()
    l1.transfer(DEPLOYER, ALICE, 10 * WAD)
    l1.approve(ALICE, gw.ADDRESS, 10 * WAD)
    gw.outbound_transfer(l1, ALICE, ALICE, 10 * WAD)
    with pytest.raises(ValueError, match="escrow insufficient"):
        gw.finalize_inbound_transfer(l1, ALICE, ALICE, 11 * WAD)
