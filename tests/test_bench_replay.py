"""bench.py's replay-headline fallback (driver-facing contract).

When the chip pool is unreachable at bench time, bench replays the best
committed bench_runs/ headline, loudly labeled. The selection must be
deterministic on any checkout and fault-isolated against malformed
evidence files.
"""
import importlib.util
import json
import os


def _load_bench():
    path = os.path.join(os.path.dirname(__file__), "..", "bench.py")
    spec = importlib.util.spec_from_file_location("bench_under_test", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _headline(value, unit="solutions/hour/chip (measured on real TPU)"):
    return {"metric": "m", "value": value, "unit": unit,
            "vs_baseline": round(value / 1800.0, 3), "stage": "headline"}


def test_replay_picks_best_value_and_labels_it(tmp_path, capsys):
    b = _load_bench()
    runs = tmp_path / "bench_runs"
    runs.mkdir()
    (runs / "a.jsonl").write_text(json.dumps(_headline(3499.0)) + "\n")
    (runs / "b.jsonl").write_text(
        json.dumps({"stage": "tiny", "value": 99999.0, "vs_baseline": 0.0})
        + "\n" + json.dumps(_headline(3600.0)) + "\n")
    (runs / "broken.jsonl").write_text("not json\n")
    (runs / "bad_types.jsonl").write_text(
        json.dumps({"stage": "headline", "value": "high",
                    "vs_baseline": "2.0"}) + "\n")
    b._REPO = str(tmp_path)
    assert b._replay_session_headline() == 1
    line = json.loads(capsys.readouterr().out.strip())
    assert line["stage"] == "replay"
    assert line["value"] == 3600.0
    assert line["unit"].startswith("REPLAY of bench_runs/b.jsonl")
    assert "not a live measurement" in line["note"]


def test_replay_emits_nothing_without_evidence(tmp_path, capsys):
    b = _load_bench()
    b._REPO = str(tmp_path)  # no bench_runs dir at all
    assert b._replay_session_headline() == 0
    assert capsys.readouterr().out == ""


def test_replay_prefers_newest_round_over_higher_old_value(tmp_path, capsys):
    """ADVICE r4: an older round's higher number must not mask a genuine
    regression in the newest round's evidence; the replayed line must be
    machine-readably flagged."""
    b = _load_bench()
    runs = tmp_path / "bench_runs"
    runs.mkdir()
    (runs / "r04_tpu_session_x.jsonl").write_text(
        json.dumps(_headline(9999.0)) + "\n")
    (runs / "r05_tpu_session_x.jsonl").write_text(
        json.dumps(_headline(3600.0)) + "\n"
        + json.dumps(_headline(3500.0)) + "\n")
    b._REPO = str(tmp_path)
    assert b._replay_session_headline() == 1
    line = json.loads(capsys.readouterr().out.strip())
    assert line["value"] == 3600.0  # best WITHIN the newest round only
    assert line["replay"] is True
    assert line["unit"].startswith("REPLAY of bench_runs/r05_tpu_session_x")
