"""RLP / EIP-1559 / JSON-RPC client tests — pinned against canonical
Ethereum vectors so the signing path is trustworthy without a network."""
from __future__ import annotations

import pytest

from arbius_tpu.chain.rlp import Eip1559Tx, rlp_encode
from arbius_tpu.chain.rpc_client import (
    ENGINE_FNS,
    EngineRpcClient,
    call_data,
    event_topic,
    selector,
)
from arbius_tpu.chain.wallet import Wallet, recover_address


# -- RLP canonical vectors (from the Ethereum wiki test set) ---------------

@pytest.mark.parametrize("value,expected", [
    (b"dog", bytes([0x83]) + b"dog"),
    ([b"cat", b"dog"], bytes([0xC8, 0x83]) + b"cat" + bytes([0x83]) + b"dog"),
    (b"", bytes([0x80])),
    ([], bytes([0xC0])),
    (0, bytes([0x80])),
    (15, bytes([0x0F])),
    (1024, bytes([0x82, 0x04, 0x00])),
    ([[], [[]], [[], [[]]]], bytes.fromhex("c7c0c1c0c3c0c1c0")),
    (b"Lorem ipsum dolor sit amet, consectetur adipisicing elit",
     bytes([0xB8, 0x38]) + b"Lorem ipsum dolor sit amet, "
     b"consectetur adipisicing elit"),
])
def test_rlp_vectors(value, expected):
    assert rlp_encode(value) == expected


# -- selectors (solc-known values) -----------------------------------------

def test_known_selectors():
    assert selector("transfer(address,uint256)").hex() == "a9059cbb"
    assert selector("balanceOf(address)").hex() == "70a08231"
    # engine fn selector matches hand-computed keccak
    sig, _ = ENGINE_FNS["signalCommitment"]
    assert sig == "signalCommitment(bytes32)"


def test_event_topic_is_keccak_of_signature():
    t = event_topic("Transfer(address,address,uint256)")
    assert t == ("0xddf252ad1be2c89b69c2b068fc378daa"
                 "952ba7f163c4a11628f55a4df523b3ef")


# -- EIP-1559 signing ------------------------------------------------------

def test_tx_signing_recovers_sender():
    w = Wallet.from_hex("0x" + "42" * 32)
    tx = Eip1559Tx(chain_id=0xA4BA, nonce=7, max_priority_fee_per_gas=10**8,
                   max_fee_per_gas=10**9, gas_limit=500_000,
                   to="0x" + "e1" * 20, value=0, data=b"\x01\x02")
    raw = tx.sign(w)
    assert raw[0] == 0x02
    # parse y,r,s back out of the RLP tail to verify recovery
    from arbius_tpu.chain.rlp import rlp_encode as enc
    # simplest check: signature over signing_hash recovers the address
    r, s, y = w.sign(tx.signing_hash())
    assert recover_address(tx.signing_hash(), r, s, y) == w.address
    # deterministic raw bytes (RFC-6979 nonce)
    assert tx.sign(w) == raw


def test_call_data_layout():
    data = call_data("signalCommitment(bytes32)", ["bytes32"],
                     [b"\xab" * 32])
    assert len(data) == 4 + 32
    assert data[4:] == b"\xab" * 32


# -- client against a fake transport ---------------------------------------

class FakeTransport:
    def __init__(self):
        self.calls = []
        self.responses = {
            "eth_blockNumber": "0x10",
            "eth_getTransactionCount": "0x5",
            "eth_gasPrice": "0x3b9aca00",          # 1 gwei
            "eth_sendRawTransaction": "0x" + "cd" * 32,
            "eth_call": "0x" + "00" * 32,
            "eth_getLogs": [],
        }

    def request(self, method, params):
        self.calls.append((method, params))
        return self.responses[method]


def test_client_send_builds_signed_tx():
    t = FakeTransport()
    client = EngineRpcClient(t, "0x" + "e1" * 20,
                             Wallet.from_hex("0x" + "11" * 32))
    tx_hash = client.send("claimSolution", [b"\x01" * 32])
    assert tx_hash == "0x" + "cd" * 32
    method, params = t.calls[-1]
    assert method == "eth_sendRawTransaction"
    raw = bytes.fromhex(params[0][2:])
    assert raw[0] == 0x02  # typed EIP-1559 envelope
    # nonce and fees were fetched first
    assert [m for m, _ in t.calls[:-1]] == [
        "eth_gasPrice", "eth_getTransactionCount"]


def test_client_eth_call_and_logs():
    t = FakeTransport()
    client = EngineRpcClient(t, "0x" + "e1" * 20,
                             Wallet.from_hex("0x" + "11" * 32))
    out = client.eth_call("solutions(bytes32)", ["bytes32"], [b"\x02" * 32])
    assert out == b"\x00" * 32
    client.get_logs("TaskSubmitted", 0, 100)
    method, params = t.calls[-1]
    assert method == "eth_getLogs"
    assert params[0]["topics"][0].startswith("0x")
