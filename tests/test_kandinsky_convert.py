"""Kandinsky-2 checkpoint-conversion tests: completeness (every leaf of the
prior/decoder/movq/text-projection trees maps to a published diffusers-format
key), bijectivity (export → convert is the identity), loud failure on
missing keys and shape mismatches, and clip-stats plumbing. Numeric
validation against real published weights is a deployment step (zero-egress
here); the boot self-test's golden CID is the production arbiter — the same
contract as tests/test_convert.py for SD-1.5.
"""
from __future__ import annotations

import jax
import numpy as np
import pytest

from arbius_tpu.models.kandinsky2 import (
    Kandinsky2Config,
    Kandinsky2Pipeline,
    convert_kandinsky2_decoder,
    convert_kandinsky2_movq,
    convert_kandinsky2_prior,
    convert_kandinsky2_text_projection,
)
from arbius_tpu.models.kandinsky2.convert import (
    decoder_key_for,
    export_tree,
    movq_key_for,
    prior_key_for,
)
from arbius_tpu.models.sd15.convert import ConversionError
from arbius_tpu.node.factory import tiny_byte_tokenizer

pytestmark = [pytest.mark.slow, pytest.mark.model]


@pytest.fixture(scope="module")
def kparams():
    cfg = Kandinsky2Config.tiny()
    pipe = Kandinsky2Pipeline(cfg, tokenizer=tiny_byte_tokenizer(cfg.text))
    return pipe.init_params(seed=7)


def _paths(tree):
    out = []
    jax.tree_util.tree_map_with_path(
        lambda p, _: out.append("/".join(
            str(getattr(k, "key", getattr(k, "idx", k))) for k in p)), tree)
    return out


def _assert_trees_equal(a, b):
    jax.tree_util.tree_map(
        lambda x, y: np.testing.assert_array_equal(np.asarray(x),
                                                   np.asarray(y)), a, b)


# -- completeness ----------------------------------------------------------

def test_every_prior_leaf_is_mapped(kparams):
    for p in _paths(kparams["prior"]):
        key, tf = prior_key_for(p)
        assert key and callable(tf)


def test_every_decoder_leaf_is_mapped(kparams):
    for p in _paths(kparams["decoder"]):
        key, tf = decoder_key_for(p)
        assert key and callable(tf)


def test_every_movq_leaf_is_mapped(kparams):
    for p in _paths(kparams["movq"]):
        key, tf = movq_key_for(p)
        assert key and callable(tf)


# -- bijectivity -----------------------------------------------------------

def test_prior_roundtrip(kparams):
    sd = export_tree(kparams["prior"], prior_key_for)
    # exported dict looks like the published prior checkpoint
    assert "time_embedding.linear_1.weight" in sd
    assert "proj_in.weight" in sd
    assert "encoder_hidden_states_proj.weight" in sd
    assert "prd_embedding" in sd
    assert any(k.startswith("transformer_blocks.0.attn1.to_q") for k in sd)
    assert "proj_to_clip_embeddings.weight" in sd
    sd["clip_mean"] = np.arange(16, dtype=np.float32)
    sd["clip_std"] = 1 + np.arange(16, dtype=np.float32)

    back, stats = convert_kandinsky2_prior(sd, kparams["prior"])
    _assert_trees_equal(kparams["prior"], back)
    assert stats.shape == (2, 16)
    np.testing.assert_array_equal(stats[0], sd["clip_mean"])
    np.testing.assert_array_equal(stats[1], sd["clip_std"])


def test_prior_missing_stats_fails(kparams):
    sd = export_tree(kparams["prior"], prior_key_for)
    sd["clip_mean"] = np.zeros(16, np.float32)  # std absent
    with pytest.raises(ConversionError, match="clip_std"):
        convert_kandinsky2_prior(sd, kparams["prior"])


def test_decoder_roundtrip(kparams):
    sd = export_tree(kparams["decoder"], decoder_key_for)
    # conditioning head uses the published image-projection naming
    assert "encoder_hid_proj.image_embeds.weight" in sd
    assert "encoder_hid_proj.norm.weight" in sd
    assert "add_embedding.linear_1.weight" in sd
    # inner unet keys are plain UNet2DConditionModel naming (no prefix),
    # in the unCLIP-style block form: added-KV attention (no transformer
    # blocks), resnet-based samplers, no attention at the top level
    assert any(k.startswith("down_blocks.0.resnets.0.") for k in sd)
    assert "down_blocks.1.attentions.0.add_k_proj.weight" in sd
    assert "down_blocks.1.attentions.0.group_norm.weight" in sd
    assert not any("transformer_blocks" in k for k in sd)
    assert not any(k.startswith("down_blocks.0.attentions") for k in sd)
    assert "down_blocks.0.downsamplers.0.conv1.weight" in sd
    assert "up_blocks.3.upsamplers.0.conv1.weight" not in sd  # final block
    assert "up_blocks.2.upsamplers.0.conv1.weight" in sd
    assert "mid_block.attentions.0.to_out.0.weight" in sd
    assert "conv_out.weight" in sd

    back = convert_kandinsky2_decoder(sd, kparams["decoder"])
    _assert_trees_equal(kparams["decoder"], back)


def test_movq_roundtrip(kparams):
    sd = export_tree(kparams["movq"], movq_key_for)
    assert "post_quant_conv.weight" in sd
    assert "decoder.conv_in.weight" in sd
    # spatially-modulated norms expose norm_layer/conv_y/conv_b triples
    assert "decoder.mid_block.resnets.0.norm1.norm_layer.weight" in sd
    assert "decoder.mid_block.resnets.0.norm1.conv_y.weight" in sd
    assert "decoder.mid_block.attentions.0.spatial_norm.conv_b.weight" in sd
    assert "decoder.mid_block.attentions.0.to_q.weight" in sd
    assert "decoder.conv_norm_out.norm_layer.weight" in sd
    # published resnet count: layers_per_block + 1 per up level
    assert "decoder.up_blocks.0.resnets.1.conv1.weight" in sd

    back = convert_kandinsky2_movq(sd, kparams["movq"])
    _assert_trees_equal(kparams["movq"], back)


def test_text_projection_roundtrip(kparams):
    sd = export_tree(kparams["text_proj"],
                     lambda p: ("text_projection.weight",
                                __import__("arbius_tpu.models.sd15.convert",
                                           fromlist=["_linear"])._linear))
    assert set(sd) == {"text_projection.weight"}
    back = convert_kandinsky2_text_projection(sd, kparams["text_proj"])
    _assert_trees_equal(kparams["text_proj"], back)


# -- failure modes ---------------------------------------------------------

def test_decoder_missing_key_fails(kparams):
    sd = export_tree(kparams["decoder"], decoder_key_for)
    sd.pop("add_embedding.linear_1.weight")
    with pytest.raises(ConversionError, match="missing"):
        convert_kandinsky2_decoder(sd, kparams["decoder"])


def test_movq_shape_mismatch_fails(kparams):
    sd = export_tree(kparams["movq"], movq_key_for)
    sd["post_quant_conv.weight"] = np.zeros((2, 2, 3, 3), np.float32)
    with pytest.raises(ConversionError, match="converted shape"):
        convert_kandinsky2_movq(sd, kparams["movq"])


# -- converted params drive the pipeline ------------------------------------

def test_converted_params_drive_the_pipeline(kparams):
    cfg = Kandinsky2Config.tiny()
    pipe = Kandinsky2Pipeline(cfg, tokenizer=tiny_byte_tokenizer(cfg.text))

    prior_sd = export_tree(kparams["prior"], prior_key_for)
    prior_sd["clip_mean"] = np.zeros(16, np.float32)
    prior_sd["clip_std"] = np.ones(16, np.float32)
    prior_tree, stats = convert_kandinsky2_prior(prior_sd, kparams["prior"])
    params = {
        "text": kparams["text"],
        "text_proj": kparams["text_proj"],
        "prior": prior_tree,
        "prior_stats": stats,
        "decoder": convert_kandinsky2_decoder(
            export_tree(kparams["decoder"], decoder_key_for),
            kparams["decoder"]),
        "movq": convert_kandinsky2_movq(
            export_tree(kparams["movq"], movq_key_for), kparams["movq"]),
    }
    a = pipe.generate(kparams, ["cat"], None, [1337], width=64, height=64,
                      num_inference_steps=2)
    b = pipe.generate(params, ["cat"], None, [1337], width=64, height=64,
                      num_inference_steps=2)
    np.testing.assert_array_equal(a, b)
    assert b.shape == (1, 64, 64, 3) and b.dtype == np.uint8
