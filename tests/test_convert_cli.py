"""convert-checkpoint CLI: published checkpoint file → orbax tree the
factory loads, exercised end-to-end with a fabricated published-format
RVM checkpoint (the smallest full-topology family, 3.8M params)."""
from __future__ import annotations

import json

import jax
import numpy as np
import pytest

from arbius_tpu.cli import main
from arbius_tpu.models.rvm import RVMPipeline, RVMPipelineConfig, RVMConfig
from arbius_tpu.models.rvm.convert import export_tree

pytestmark = [pytest.mark.slow, pytest.mark.model]


def test_rvm_checkpoint_roundtrip(tmp_path, capsys):
    # fabricate a published-format checkpoint from a real full-topology
    # init (torch-hub envelope + an extra num_batches_tracked entry)
    pipe = RVMPipeline(RVMPipelineConfig())
    params = pipe.init_params(seed=3)
    sd = export_tree(params, RVMConfig())
    sd["backbone.features.0.1.num_batches_tracked"] = np.int64(7)
    import torch

    ckpt = tmp_path / "rvm_mobilenetv3.pth"
    torch.save({"state_dict": {k: torch.from_numpy(np.asarray(v))
                               if isinstance(v, np.ndarray) else torch.tensor(v)
                               for k, v in sd.items()}}, ckpt)

    out = tmp_path / "rvm_orbax"
    assert main(["convert-checkpoint", "--family", "robust_video_matting",
                 "--weights", str(ckpt), "--out", str(out)]) == 0
    info = json.loads(capsys.readouterr().out.strip())
    assert info["family"] == "robust_video_matting"
    assert info["param_count"] == sum(
        x.size for x in jax.tree_util.tree_leaves(params))

    # the factory's load path must restore the identical tree
    from arbius_tpu.utils import load_params

    restored = load_params(str(out))
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_array_equal(
            np.asarray(a), np.asarray(b)), params, restored)


def test_missing_component_is_clean_error(tmp_path):
    with pytest.raises(SystemExit, match="--weights is required"):
        main(["convert-checkpoint", "--family", "robust_video_matting",
              "--out", str(tmp_path / "x")])


def test_record_golden_reproducible_and_boot_wirable(capsys):
    """record-golden output must reproduce bit-exactly and drop into
    ModelConfig.golden, where the factory wires it for boot's self-test
    (the reference's pinned-CID check, index.ts:984-1001)."""
    argv = ["record-golden", "--template", "anythingv3", "--tiny",
            "--input", json.dumps({
                "prompt": "arbius test cat", "negative_prompt": "",
                "width": 128, "height": 128, "num_inference_steps": 2,
                "scheduler": "DDIM"})]
    assert main(argv) == 0
    rec1 = json.loads(capsys.readouterr().out.strip())
    assert main(argv) == 0
    rec2 = json.loads(capsys.readouterr().out.strip())
    assert rec1["golden"] == rec2["golden"]          # bit-stable
    assert rec1["golden"]["cid"].startswith("0x1220")
    assert rec1["golden"]["seed"] == 1337            # index.ts:988

    # the snippet drops straight into config → factory → boot self-test
    from arbius_tpu.node.config import MiningConfig, ModelConfig
    from arbius_tpu.node.factory import build_registry
    from arbius_tpu.node.solver import solve_cid
    from arbius_tpu.templates.engine import hydrate_input

    mid = "0x" + "ab" * 32
    cfg = MiningConfig(models=(ModelConfig(
        id=mid, template="anythingv3", tiny=True,
        golden=rec1["golden"]),))
    reg = build_registry(cfg)
    m = reg.get(mid)
    inp, seed, expected = m.golden
    got, _ = solve_cid(m, hydrate_input(dict(inp), m.template), seed)
    assert got == expected                            # boot would pass
