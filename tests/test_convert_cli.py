"""convert-checkpoint CLI: published checkpoint file → orbax tree the
factory loads, exercised end-to-end with a fabricated published-format
RVM checkpoint (the smallest full-topology family, 3.8M params)."""
from __future__ import annotations

import json

import jax
import numpy as np
import pytest

from arbius_tpu.cli import main
from arbius_tpu.models.rvm import RVMPipeline, RVMPipelineConfig, RVMConfig
from arbius_tpu.models.rvm.convert import export_tree

pytestmark = [pytest.mark.slow, pytest.mark.model]


def test_rvm_checkpoint_roundtrip(tmp_path, capsys):
    # fabricate a published-format checkpoint from a real full-topology
    # init (torch-hub envelope + an extra num_batches_tracked entry)
    pipe = RVMPipeline(RVMPipelineConfig())
    params = pipe.init_params(seed=3)
    sd = export_tree(params, RVMConfig())
    sd["backbone.features.0.1.num_batches_tracked"] = np.int64(7)
    import torch

    ckpt = tmp_path / "rvm_mobilenetv3.pth"
    torch.save({"state_dict": {k: torch.from_numpy(np.asarray(v))
                               if isinstance(v, np.ndarray) else torch.tensor(v)
                               for k, v in sd.items()}}, ckpt)

    out = tmp_path / "rvm_orbax"
    assert main(["convert-checkpoint", "--family", "robust_video_matting",
                 "--weights", str(ckpt), "--out", str(out)]) == 0
    info = json.loads(capsys.readouterr().out.strip())
    assert info["family"] == "robust_video_matting"
    assert info["param_count"] == sum(
        x.size for x in jax.tree_util.tree_leaves(params))

    # the factory's load path must restore the identical tree
    from arbius_tpu.utils import load_params

    restored = load_params(str(out))
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_array_equal(
            np.asarray(a), np.asarray(b)), params, restored)


def test_missing_component_is_clean_error(tmp_path):
    with pytest.raises(SystemExit, match="--weights is required"):
        main(["convert-checkpoint", "--family", "robust_video_matting",
              "--out", str(tmp_path / "x")])
