"""Production-composition test: the real `MinerNode.run()` wall-clock loop
driving mining in a background thread while `ControlRPC` serves the
operator API — the exact process shape `node-run` assembles
(`miner/src/start.ts:11-52`: RPC server up, then main loop forever).

Every other node test drives `tick()` directly for determinism; this one
covers the composition those tests skip: run()'s poll cadence, the stop
flag, and concurrent RPC reads against a live node.
"""
from __future__ import annotations

import json
import threading
import time
import urllib.request

from arbius_tpu.node.rpc import ControlRPC

from test_node import build_world, submit


def test_run_loop_mines_and_serves_rpc():
    eng, tok, chain, node, mid = build_world(poll_interval_ms=5)
    rpc = ControlRPC(node)
    rpc.start()
    stop = threading.Event()
    t = threading.Thread(target=node.run, kwargs={"stop": stop.is_set},
                         daemon=True)
    t.start()
    try:
        tid = submit(eng, mid)
        key = bytes.fromhex(tid[2:])
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline and key not in eng.solutions:
            time.sleep(0.02)
        assert key in eng.solutions, "run() loop never solved the task"
        assert eng.solutions[key].validator == chain.address

        url = f"http://127.0.0.1:{rpc.port}/api/metrics"
        with urllib.request.urlopen(url, timeout=5) as resp:
            metrics = json.load(resp)
        assert metrics["solutions_submitted"] >= 1

        with urllib.request.urlopen(
                f"http://127.0.0.1:{rpc.port}/api/tasks", timeout=5) as resp:
            tasks = json.load(resp)
        assert any(row["taskid"] == tid for row in tasks)
    finally:
        stop.set()
        t.join(timeout=10)
        rpc.stop()
    assert not t.is_alive(), "run() did not honor the stop flag"
