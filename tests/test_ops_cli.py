"""Ops CLI verbs against a served devnet — the hardhat-task parity layer
(`contract/tasks/index.ts:12-465`): register → stake → submit → solve →
claim, and the full governance lifecycle, all through `arbius_tpu.cli`
with real signed transactions over HTTP JSON-RPC.
"""
from __future__ import annotations

import json
import threading

import pytest

from arbius_tpu.chain import Engine, TokenLedger, WAD, Wallet
from arbius_tpu.chain.devnet import DevnetNode
from arbius_tpu.chain.governance import (
    TIMELOCK_MIN_DELAY,
    VOTING_DELAY,
    VOTING_PERIOD,
)
from arbius_tpu.chain.rpc_client import EngineRpcClient, JsonRpcTransport
from arbius_tpu.cli import main
from arbius_tpu.l0.cid import cid_hex, cid_of_solution_files
from arbius_tpu.l0.commitment import generate_commitment

CHAIN_ID = 31337


@pytest.fixture()
def world(tmp_path):
    operator = Wallet.generate()
    miner = Wallet.generate()
    tok = TokenLedger()
    eng = Engine(tok, start_time=1000)
    tok.mint(Engine.ADDRESS, 600_000 * WAD)
    tok.mint(operator.address.lower(), 100_000 * WAD)
    tok.mint(miner.address.lower(), 10_000 * WAD)
    dev = DevnetNode(eng, chain_id=CHAIN_ID)
    server = dev.serve("127.0.0.1", 0)
    port = server.server_address[1]
    t = threading.Thread(target=server.serve_forever, daemon=True)
    t.start()
    dep_path = tmp_path / "deployment.json"
    dep_path.write_text(json.dumps({
        "rpc_url": f"http://127.0.0.1:{port}",
        "engine_address": dev.engine_address,
        "token_address": dev.token_address,
        "governor_address": dev.governor_address,
        "chain_id": CHAIN_ID,
    }))
    try:
        yield eng, dev, operator, miner, str(dep_path)
    finally:
        server.shutdown()


def run_cli(capsys, argv) -> dict:
    assert main(argv) == 0
    return json.loads(capsys.readouterr().out.strip())


def test_register_stake_submit_solve_claim(world, capsys, tmp_path):
    eng, dev, operator, miner, dep = world
    base = ["--deployment", dep]

    # model:register — bundled template, derived id matches the engine's
    reg = run_cli(capsys, ["model-register", *base, "--key", "0x" + operator.private_key.hex(),
                           "--template", "anythingv3"])
    mid = reg["model_id"]
    assert bytes.fromhex(mid[2:]) in eng.models

    # validator:stake — approve + deposit to minimum*1.1
    st = run_cli(capsys, ["validator-stake", *base, "--key", "0x" + miner.private_key.hex()])
    assert int(st["staked_wad"]) >= eng.get_validator_minimum()

    # task-submit — hydrate-validated input, taskid from the log
    sub = run_cli(capsys, ["task-submit", *base, "--key", "0x" + operator.private_key.hex(),
                           "--model", mid, "--template", "anythingv3",
                           "--fee", "10",
                           "--input", json.dumps({
                               "prompt": "ops cli", "negative_prompt": ""})])
    taskid = sub["taskid"]
    assert taskid and bytes.fromhex(taskid[2:]) in eng.tasks

    # solve out-of-band through the same signed-tx client (the node's job;
    # here the CLI test only needs a claimable solution on-chain)
    client = EngineRpcClient(JsonRpcTransport(dep_url(dep)),
                             dev.engine_address, miner, chain_id=CHAIN_ID)
    cid = cid_hex(cid_of_solution_files({"out-1.png": b"\x89PNGfake"}))
    commitment = generate_commitment(miner.address, taskid, cid)
    client.send("signalCommitment", [commitment])
    run_cli(capsys, ["timetravel", "--deployment", dep, "--blocks", "1"])
    client.send("submitSolution", [taskid, cid])

    status = run_cli(capsys, ["task-status", *base, taskid])
    assert status["solution"]["validator"] == miner.address.lower()
    assert status["solution"]["cid"] == cid
    assert status["solution"]["claimed"] is False

    # claim is time-gated (EngineV1.sol:255: minClaimSolutionTime=2000)
    run_cli(capsys, ["timetravel", "--deployment", dep, "--seconds", "2120",
                     "--blocks", "1"])
    bal0 = run_cli(capsys, ["balance", *base, "--key", "0x" + miner.private_key.hex()])
    run_cli(capsys, ["claim", *base, "--key", "0x" + miner.private_key.hex(), taskid])
    status = run_cli(capsys, ["task-status", *base, taskid])
    assert status["solution"]["claimed"] is True
    bal1 = run_cli(capsys, ["balance", *base, "--key", "0x" + miner.private_key.hex()])
    assert int(bal1["balance_wad"]) > int(bal0["balance_wad"])  # emission


def test_governance_lifecycle(world, capsys):
    eng, dev, operator, miner, dep = world
    base = ["--deployment", dep, "--key", "0x" + operator.private_key.hex()]

    reg = run_cli(capsys, ["model-register", "--deployment", dep,
                           "--key", "0x" + operator.private_key.hex(),
                           "--template", "kandinsky2"])
    mid = reg["model_id"]
    rate = 10**18

    run_cli(capsys, ["governance", "delegate", *base])
    run_cli(capsys, ["timetravel", "--deployment", dep, "--blocks", "1"])

    prop = run_cli(capsys, [
        "governance", "propose", *base,
        "--fn", "setSolutionMineableRate(bytes32,uint256)",
        "--args", mid, str(rate),
        "--description", "make kandinsky2 mineable"])
    pid = prop["proposal_id"]

    view = run_cli(capsys, ["governance", "proposal", "--deployment", dep,
                            "--pid", pid])
    assert view["state"] == "PENDING"

    run_cli(capsys, ["timetravel", "--deployment", dep,
                     "--blocks", str(VOTING_DELAY + 1)])
    run_cli(capsys, ["governance", "vote", *base, "--pid", pid,
                     "--support", "1"])
    view = run_cli(capsys, ["governance", "proposal", "--deployment", dep,
                            "--pid", pid])
    assert int(view["votes"]["for"]) >= 100_000 * WAD

    run_cli(capsys, ["timetravel", "--deployment", dep,
                     "--blocks", str(VOTING_PERIOD + 1)])
    run_cli(capsys, ["governance", "queue", *base, "--pid", pid])
    run_cli(capsys, ["timetravel", "--deployment", dep,
                     "--seconds", str(TIMELOCK_MIN_DELAY + 1), "--blocks", "1"])
    run_cli(capsys, ["governance", "execute", *base, "--pid", pid])

    assert eng.models[bytes.fromhex(mid[2:])].rate == rate
    view = run_cli(capsys, ["governance", "proposal", "--deployment", dep,
                            "--pid", pid])
    assert view["state"] == "EXECUTED"


def test_unauthorized_governance_call_refused(world, capsys):
    """Proposals may only call the governance-gated admin surface."""
    eng, dev, operator, miner, dep = world
    run_cli(capsys, ["governance", "delegate", "--deployment", dep,
                     "--key", "0x" + operator.private_key.hex()])
    run_cli(capsys, ["timetravel", "--deployment", dep, "--blocks", "1"])
    from arbius_tpu.chain.rpc_client import RpcError

    with pytest.raises(RpcError, match="no governance-executable call"):
        main(["governance", "propose", "--deployment", dep,
              "--key", "0x" + operator.private_key.hex(),
              "--fn", "validatorDeposit(address,uint256)",
              "--args", operator.address, "1",
              "--description", "sneaky"])


def test_unknown_proposal_reverts_cleanly(world, capsys):
    """A typo'd pid must surface as a revert, not a raw KeyError."""
    eng, dev, operator, miner, dep = world
    from arbius_tpu.chain.rpc_client import RpcError

    with pytest.raises(RpcError, match="unknown proposal"):
        main(["governance", "vote", "--deployment", dep,
              "--key", "0x" + operator.private_key.hex(),
              "--pid", "0x" + "99" * 32])
    with pytest.raises(RpcError, match="unknown proposal"):
        main(["governance", "proposal", "--deployment", dep,
              "--pid", "0x" + "99" * 32])


def test_evm_mine_timestamp_semantics(world):
    """evm_mine's optional param is a block TIMESTAMP (ganache/hardhat),
    not a count — the count batch lives under hardhat_mine."""
    eng, dev, operator, miner, dep = world
    before_block, before_now = eng.block_number, eng.now
    dev.request("evm_mine", [hex(before_now + 500)])
    assert eng.block_number == before_block + 1
    assert eng.now >= before_now + 500
    dev.request("hardhat_mine", [hex(10)])
    assert eng.block_number == before_block + 11


def test_task_status_unknown_task_errors(world, capsys):
    eng, dev, operator, miner, dep = world
    assert main(["task-status", "--deployment", dep,
                 "0x" + "42" * 32]) == 1
    out = json.loads(capsys.readouterr().out.strip())
    assert out["error"] == "task not found"


def dep_url(dep_path: str) -> str:
    return json.loads(open(dep_path).read())["rpc_url"]


def test_same_description_distinct_actions_distinct_pids(world, capsys):
    """OZ binds the proposal id to the calldata; the devnet surface must
    too — same description, different action, different id."""
    eng, dev, operator, miner, dep = world
    base = ["--deployment", dep, "--key", "0x" + operator.private_key.hex()]
    run_cli(capsys, ["governance", "delegate", *base])
    run_cli(capsys, ["timetravel", "--deployment", dep, "--blocks", "1"])
    p1 = run_cli(capsys, ["governance", "propose", *base,
                          "--fn", "setPaused(bool)", "--args", "true",
                          "--description", "maintenance"])
    p2 = run_cli(capsys, ["governance", "propose", *base,
                          "--fn", "setPaused(bool)", "--args", "false",
                          "--description", "maintenance"])
    assert p1["proposal_id"] and p2["proposal_id"]
    assert p1["proposal_id"] != p2["proposal_id"]


def test_failed_execution_leaves_proposal_queued(world, capsys):
    """No EVM rollback in-process: a reverting action must leave the
    proposal re-executable (QUEUED), not EXECUTED-with-no-effect."""
    from arbius_tpu.chain.rpc_client import RpcError

    eng, dev, operator, miner, dep = world
    base = ["--deployment", dep, "--key", "0x" + operator.private_key.hex()]
    run_cli(capsys, ["governance", "delegate", *base])
    run_cli(capsys, ["timetravel", "--deployment", dep, "--blocks", "1"])
    prop = run_cli(capsys, [
        "governance", "propose", *base,
        "--fn", "setSolutionMineableRate(bytes32,uint256)",
        "--args", "0x" + "ee" * 32, "7",  # model never registered
        "--description", "rate on a ghost model"])
    pid = prop["proposal_id"]
    run_cli(capsys, ["timetravel", "--deployment", dep,
                     "--blocks", str(VOTING_DELAY + 1)])
    run_cli(capsys, ["governance", "vote", *base, "--pid", pid,
                     "--support", "1"])
    run_cli(capsys, ["timetravel", "--deployment", dep,
                     "--blocks", str(VOTING_PERIOD + 1)])
    run_cli(capsys, ["governance", "queue", *base, "--pid", pid])
    run_cli(capsys, ["timetravel", "--deployment", dep,
                     "--seconds", str(TIMELOCK_MIN_DELAY + 1), "--blocks", "1"])
    with pytest.raises(RpcError, match="model does not exist"):
        main(["governance", "execute", *base, "--pid", pid])
    capsys.readouterr()
    view = run_cli(capsys, ["governance", "proposal", "--deployment", dep,
                            "--pid", pid])
    assert view["state"] == "QUEUED"  # still re-executable


def test_transfer_decode_tx_and_treasury_withdraw(world, capsys):
    """mining:transfer, decode-tx, treasury:withdrawAccruedFees parity."""
    eng, dev, operator, miner, dep = world
    base = ["--deployment", dep, "--key", "0x" + operator.private_key.hex()]

    out = run_cli(capsys, ["transfer", *base, "--to", miner.address,
                           "--amount", "2.5"])
    assert int(out["amount_wad"]) == 25 * 10**17
    bal = run_cli(capsys, ["balance", "--deployment", dep,
                           "--address", miner.address])
    assert int(bal["balance_wad"]) == 10_000 * WAD + 25 * 10**17

    # decode a raw signed transfer tx (decode-tx is offline: no endpoint)
    from arbius_tpu.chain.rlp import Eip1559Tx
    from arbius_tpu.chain.rpc_client import call_data

    tx = Eip1559Tx(chain_id=CHAIN_ID, nonce=7, max_priority_fee_per_gas=1,
                   max_fee_per_gas=100, gas_limit=21000,
                   to=dev.token_address, value=0,
                   data=call_data("transfer(address,uint256)",
                                  ["address", "uint256"],
                                  [miner.address, 5 * WAD]))
    raw = "0x" + tx.sign(operator).hex()
    dec = run_cli(capsys, ["decode-tx", raw])
    assert dec["from"] == operator.address.lower()
    assert dec["to"] == dev.token_address
    assert dec["selector"] == "0xa9059cbb"  # transfer(address,uint256)
    assert dec["nonce"] == 7

    # sweep accrued protocol fees to the treasury (accrual paths —
    # claim fee share, retraction cut — are covered by the engine tests;
    # here the verb itself is under test)
    eng.accrued_fees = 5 * WAD
    sw = run_cli(capsys, ["treasury-withdraw", *base])
    assert int(sw["accrued_wad_before"]) == 5 * WAD
    assert eng.accrued_fees == 0                      # swept on-chain
    assert eng.token.balance_of(eng.treasury) == 5 * WAD


def test_governance_cancel(world, capsys):
    """governance:cancel parity — proposer cancels while PENDING."""
    eng, dev, operator, miner, dep = world
    base = ["--deployment", dep, "--key", "0x" + operator.private_key.hex()]
    run_cli(capsys, ["governance", "delegate", *base])
    run_cli(capsys, ["timetravel", "--deployment", dep, "--blocks", "1"])
    prop = run_cli(capsys, ["governance", "propose", *base,
                            "--fn", "setPaused(bool)", "--args", "true",
                            "--description", "cancel me"])
    pid = prop["proposal_id"]
    run_cli(capsys, ["governance", "cancel", *base, "--pid", pid])
    view = run_cli(capsys, ["governance", "proposal", "--deployment", dep,
                            "--pid", pid])
    assert view["state"] == "CANCELED"
    from arbius_tpu.chain.rpc_client import RpcError

    with pytest.raises(RpcError, match="not active"):
        main(["governance", "vote", *base, "--pid", pid, "--support", "1"])


def test_engine_admin_owner_gated(world, capsys):
    """engine:pause / setVersion parity: pauser/owner-gated direct admin
    writes; unauthorized senders revert, unconfigured roles authorize
    nobody over RPC."""
    from arbius_tpu.chain.rpc_client import RpcError

    eng, dev, operator, miner, dep = world
    op = ["--deployment", dep, "--key", "0x" + operator.private_key.hex()]
    other = ["--deployment", dep, "--key", "0x" + miner.private_key.hex()]

    # roles unconfigured: nobody may admin over RPC
    with pytest.raises(RpcError, match="not pauser"):
        main(["engine-admin", "pause", "true", *op])

    eng.owner = eng.pauser = operator.address.lower()
    out = run_cli(capsys, ["engine-admin", "pause", "true", *op])
    assert out["paused"] is True and eng.paused is True
    with pytest.raises(RpcError, match="not pauser"):
        main(["engine-admin", "pause", "false", *other])
    run_cli(capsys, ["engine-admin", "pause", "false", *op])
    assert eng.paused is False

    run_cli(capsys, ["engine-admin", "set-version", "3", *op])
    assert eng.version == 3
    with pytest.raises(RpcError, match="not owner"):
        main(["engine-admin", "set-version", "4", *other])

    # hand the pauser role to the miner; owner stays with the operator
    run_cli(capsys, ["engine-admin", "transfer-pauser",
                     miner.address, *op])
    run_cli(capsys, ["engine-admin", "pause", "true", *other])
    assert eng.paused is True


def test_transfer_ownership_rejects_zero_address(world, capsys):
    eng, dev, operator, miner, dep = world
    from arbius_tpu.chain.rpc_client import RpcError

    eng.owner = eng.pauser = operator.address.lower()
    op = ["--deployment", dep, "--key", "0x" + operator.private_key.hex()]
    with pytest.raises(RpcError, match="zero address"):
        main(["engine-admin", "transfer-ownership",
              "0x" + "00" * 20, *op])
    assert eng.owner == operator.address.lower()  # unchanged


def test_task_retract_and_signal_support(world, capsys):
    """retractTask + mining:signalSupport parity over signed txs."""
    from arbius_tpu.chain.rpc_client import RpcError

    eng, dev, operator, miner, dep = world
    op = ["--deployment", dep, "--key", "0x" + operator.private_key.hex()]
    mi = ["--deployment", dep, "--key", "0x" + miner.private_key.hex()]

    reg = run_cli(capsys, ["model-register", *op,
                           "--template", "anythingv3"])
    mid = reg["model_id"]

    # signal-support (validator gating itself is covered by the engine
    # suite; this world's pseudo-supply keeps the minimum at zero)
    with pytest.raises(RpcError, match="model does not exist"):
        main(["signal-support", *mi, "--model", "0x" + "77" * 32])
    run_cli(capsys, ["validator-stake", *mi])
    out = run_cli(capsys, ["signal-support", *mi, "--model", mid,
                           "--support", "true"])
    assert out["support"] is True
    assert eng.events[-1].name == "SignalSupport"
    assert eng.events[-1].args["model"] == bytes.fromhex(mid[2:])

    # retract: fee comes back minus the 10% retraction cut
    sub = run_cli(capsys, ["task-submit", *op, "--model", mid,
                           "--template", "anythingv3", "--fee", "10",
                           "--input", json.dumps({
                               "prompt": "r", "negative_prompt": ""})])
    tid = sub["taskid"]
    with pytest.raises(RpcError, match="did not wait"):
        main(["task-retract", *op, tid])
    run_cli(capsys, ["timetravel", "--deployment", dep,
                     "--seconds", "10001", "--blocks", "1"])
    bal0 = int(run_cli(capsys, ["balance", "--deployment", dep,
                                "--address", operator.address])
               ["balance_wad"])
    run_cli(capsys, ["task-retract", *op, tid])
    bal1 = int(run_cli(capsys, ["balance", "--deployment", dep,
                                "--address", operator.address])
               ["balance_wad"])
    assert bal1 - bal0 == 9 * WAD          # 10 minus 10% cut
    assert eng.accrued_fees == 1 * WAD     # cut accrued to treasury


def test_governance_pause_respects_transferred_pauser(world, capsys):
    """EngineV1 fidelity: the timelock executes as the governor identity,
    so once the pauser role moves elsewhere a governance setPaused must
    revert exactly as onlyPauser would on-chain."""
    from arbius_tpu.chain.rpc_client import RpcError

    eng, dev, operator, miner, dep = world
    op = ["--deployment", dep, "--key", "0x" + operator.private_key.hex()]
    # production posture: the timelock/governor holds the roles
    eng.owner = eng.pauser = dev.governor_address
    run_cli(capsys, ["governance", "delegate", *op])
    run_cli(capsys, ["timetravel", "--deployment", dep, "--blocks", "1"])
    prop = run_cli(capsys, ["governance", "propose", *op,
                            "--fn", "setPaused(bool)", "--args", "true",
                            "--description", "pause via timelock"])
    pid = prop["proposal_id"]
    run_cli(capsys, ["timetravel", "--deployment", dep,
                     "--blocks", str(VOTING_DELAY + 1)])
    run_cli(capsys, ["governance", "vote", *op, "--pid", pid,
                     "--support", "1"])
    run_cli(capsys, ["timetravel", "--deployment", dep,
                     "--blocks", str(VOTING_PERIOD + 1)])
    run_cli(capsys, ["governance", "queue", *op, "--pid", pid])
    run_cli(capsys, ["timetravel", "--deployment", dep,
                     "--seconds", str(TIMELOCK_MIN_DELAY + 1),
                     "--blocks", "1"])
    # timelock holds pauser: executes
    run_cli(capsys, ["governance", "execute", *op, "--pid", pid])
    assert eng.paused is True
    eng.paused = False

    # move pauser away from the timelock; a second pause proposal must
    # now revert at execution (proposal stays QUEUED)
    eng.pauser = operator.address.lower()
    prop2 = run_cli(capsys, ["governance", "propose", *op,
                             "--fn", "setPaused(bool)", "--args", "true",
                             "--description", "pause after handoff"])
    pid2 = prop2["proposal_id"]
    run_cli(capsys, ["timetravel", "--deployment", dep,
                     "--blocks", str(VOTING_DELAY + 1)])
    run_cli(capsys, ["governance", "vote", *op, "--pid", pid2,
                     "--support", "1"])
    run_cli(capsys, ["timetravel", "--deployment", dep,
                     "--blocks", str(VOTING_PERIOD + 1)])
    run_cli(capsys, ["governance", "queue", *op, "--pid", pid2])
    run_cli(capsys, ["timetravel", "--deployment", dep,
                     "--seconds", str(TIMELOCK_MIN_DELAY + 1),
                     "--blocks", "1"])
    with pytest.raises(RpcError, match="not pauser"):
        main(["governance", "execute", *op, "--pid", pid2])
    assert eng.paused is False


def test_governance_rate_respects_transferred_owner(world, capsys):
    """Mirror of the pauser case for the owner role: once ownership moves
    off the timelock, a governance setSolutionMineableRate must revert at
    execution exactly as onlyOwner would on-chain."""
    from arbius_tpu.chain.rpc_client import RpcError

    eng, dev, operator, miner, dep = world
    op = ["--deployment", dep, "--key", "0x" + operator.private_key.hex()]
    reg = run_cli(capsys, ["model-register", *op,
                           "--template", "anythingv3"])
    mid = reg["model_id"]
    eng.owner = eng.pauser = operator.address.lower()  # NOT the timelock
    run_cli(capsys, ["governance", "delegate", *op])
    run_cli(capsys, ["timetravel", "--deployment", dep, "--blocks", "1"])
    prop = run_cli(capsys, [
        "governance", "propose", *op,
        "--fn", "setSolutionMineableRate(bytes32,uint256)",
        "--args", mid, "7", "--description", "rate sans ownership"])
    pid = prop["proposal_id"]
    run_cli(capsys, ["timetravel", "--deployment", dep,
                     "--blocks", str(VOTING_DELAY + 1)])
    run_cli(capsys, ["governance", "vote", *op, "--pid", pid,
                     "--support", "1"])
    run_cli(capsys, ["timetravel", "--deployment", dep,
                     "--blocks", str(VOTING_PERIOD + 1)])
    run_cli(capsys, ["governance", "queue", *op, "--pid", pid])
    run_cli(capsys, ["timetravel", "--deployment", dep,
                     "--seconds", str(TIMELOCK_MIN_DELAY + 1),
                     "--blocks", "1"])
    with pytest.raises(RpcError, match="not owner"):
        main(["governance", "execute", *op, "--pid", pid])
    # hand ownership to the timelock: the retry now applies
    eng.owner = dev.governor_address
    run_cli(capsys, ["governance", "execute", *op, "--pid", pid])
    assert eng.models[bytes.fromhex(mid[2:])].rate == 7


def test_governance_tunes_protocol_parameter(world, capsys):
    """Every EngineV1 owner setter is governable: tune
    minClaimSolutionTime through the full proposal lifecycle."""
    eng, dev, operator, miner, dep = world
    op = ["--deployment", dep, "--key", "0x" + operator.private_key.hex()]
    assert eng.min_claim_solution_time == 2000
    run_cli(capsys, ["governance", "delegate", *op])
    run_cli(capsys, ["timetravel", "--deployment", dep, "--blocks", "1"])
    prop = run_cli(capsys, [
        "governance", "propose", *op,
        "--fn", "setMinClaimSolutionTime(uint256)", "--args", "3600",
        "--description", "longer claim window"])
    pid = prop["proposal_id"]
    run_cli(capsys, ["timetravel", "--deployment", dep,
                     "--blocks", str(VOTING_DELAY + 1)])
    run_cli(capsys, ["governance", "vote", *op, "--pid", pid,
                     "--support", "1"])
    run_cli(capsys, ["timetravel", "--deployment", dep,
                     "--blocks", str(VOTING_PERIOD + 1)])
    run_cli(capsys, ["governance", "queue", *op, "--pid", pid])
    run_cli(capsys, ["timetravel", "--deployment", dep,
                     "--seconds", str(TIMELOCK_MIN_DELAY + 1),
                     "--blocks", "1"])
    run_cli(capsys, ["governance", "execute", *op, "--pid", pid])
    assert eng.min_claim_solution_time == 3600
    assert eng.events[-2].name == "ParamChanged"   # then ProposalExecuted


def test_owner_sets_parameter_directly(world, capsys):
    """Direct owner path for the same setters, and treasury transfer."""
    from arbius_tpu.chain.rpc_client import RpcError
    from arbius_tpu.chain.rpc_client import EngineRpcClient

    eng, dev, operator, miner, dep = world
    eng.owner = eng.pauser = operator.address.lower()
    client = EngineRpcClient(dev, dev.engine_address, operator,
                             chain_id=CHAIN_ID)
    client.send_to(dev.engine_address,
                   "setSolutionFeePercentage(uint256)", ["uint256"],
                   [2 * 10**17])
    assert eng.solution_fee_percentage == 2 * 10**17
    # read back over the RPC view surface (public-var accessor)
    from arbius_tpu.l0.abi import abi_decode

    got = abi_decode(["uint256"], client.eth_call(
        "solutionFeePercentage()", [], []))[0]
    assert got == 2 * 10**17
    client.send_to(dev.engine_address, "transferTreasury(address)",
                   ["address"], [miner.address])
    assert eng.treasury == miner.address.lower()
    bad = EngineRpcClient(dev, dev.engine_address, miner,
                          chain_id=CHAIN_ID)
    with pytest.raises(RpcError, match="not owner"):
        bad.send_to(dev.engine_address,
                    "setSolutionFeePercentage(uint256)", ["uint256"], [1])


def test_task_submit_sign_only_roundtrip(world, capsys):
    """`task-submit --sign-only` prints a raw EIP-1559 tx instead of
    sending; forwarding those bytes via eth_sendRawTransaction lands the
    task under the SIGNER's address — the CLI half of the dapp's
    /api/tx/raw user-wallet path."""
    eng, dev, operator, miner, dep = world
    base = ["--deployment", dep]
    reg = run_cli(capsys, ["model-register", *base,
                           "--key", "0x" + operator.private_key.hex(),
                           "--template", "anythingv3"])
    mid = reg["model_id"]

    out = run_cli(capsys, ["task-submit", *base,
                           "--key", "0x" + operator.private_key.hex(),
                           "--model", mid, "--template", "anythingv3",
                           "--input", json.dumps({
                               "prompt": "signed offline",
                               "negative_prompt": ""}),
                           "--sign-only"])
    assert out["raw"].startswith("0x02")
    assert out["from"] == operator.address
    n_before = len(eng.tasks)

    client = EngineRpcClient(JsonRpcTransport(dep_url(dep)),
                             dev.engine_address, miner, chain_id=CHAIN_ID)
    client.transport.request("eth_sendRawTransaction", [out["raw"]])
    assert len(eng.tasks) == n_before + 1
    task = list(eng.tasks.values())[-1]
    assert task.owner == operator.address.lower()
