"""Template engine tests — hydration semantics from `miner/src/models.ts:145-220`."""
import pytest

from arbius_tpu.templates import (
    FilterResult,
    HydrationError,
    MiningFilter,
    Template,
    check_model_filter,
    hydrate_input,
    load_template,
    template_names,
)


def test_all_reference_templates_parse():
    names = template_names()
    assert names == sorted(
        ["anythingv3", "kandinsky2", "zeroscopev2xl", "damo",
         "robust_video_matting", "textgen"])
    for n in names:
        t = load_template(n)
        assert t.title
        assert t.outputs


def test_anythingv3_schema():
    t = load_template("anythingv3")
    byname = {f.variable: f for f in t.inputs}
    assert byname["scheduler"].choices == (
        "DDIM", "K_EULER", "DPMSolverMultistep", "K_EULER_ANCESTRAL", "PNDM", "KLMS")
    assert byname["width"].default == 768
    assert byname["num_inference_steps"].max == 500
    assert t.outputs[0].filename == "out-1.png"


class TestHydration:
    @pytest.fixture()
    def t(self):
        return load_template("anythingv3")

    def test_defaults_filled(self, t):
        out = hydrate_input({"prompt": "cat", "negative_prompt": ""}, t)
        assert out["width"] == 768
        assert out["height"] == 768
        assert out["num_inference_steps"] == 20
        assert out["guidance_scale"] == 12
        assert out["scheduler"] == "DPMSolverMultistep"

    def test_missing_required(self, t):
        with pytest.raises(HydrationError, match="missing required field \\(prompt\\)"):
            hydrate_input({"negative_prompt": ""}, t)

    def test_wrong_type_string(self, t):
        with pytest.raises(HydrationError, match="wrong type"):
            hydrate_input({"prompt": 5, "negative_prompt": ""}, t)

    def test_int_rejects_float_and_bool(self, t):
        with pytest.raises(HydrationError, match="wrong type"):
            hydrate_input({"prompt": "x", "negative_prompt": "", "num_inference_steps": 20.5}, t)
        with pytest.raises(HydrationError, match="wrong type"):
            hydrate_input({"prompt": "x", "negative_prompt": "", "num_inference_steps": True}, t)

    def test_decimal_accepts_fraction(self, t):
        # divergence from reference bug models.ts:185-188 (documented)
        out = hydrate_input({"prompt": "x", "negative_prompt": "", "guidance_scale": 17.5}, t)
        assert out["guidance_scale"] == 17.5

    def test_range_enforced_both_ends(self, t):
        # reference bug models.ts:194 never enforced max; we do
        with pytest.raises(HydrationError, match="out of bounds"):
            hydrate_input({"prompt": "x", "negative_prompt": "", "num_inference_steps": 501}, t)
        with pytest.raises(HydrationError, match="out of bounds"):
            hydrate_input({"prompt": "x", "negative_prompt": "", "num_inference_steps": 0}, t)

    def test_enum_membership(self, t):
        with pytest.raises(HydrationError, match="not in enum"):
            hydrate_input({"prompt": "x", "negative_prompt": "", "width": 333}, t)
        with pytest.raises(HydrationError, match="not in enum"):
            hydrate_input({"prompt": "x", "negative_prompt": "", "scheduler": "UniPC"}, t)

    def test_extra_fields_dropped(self, t):
        out = hydrate_input({"prompt": "x", "negative_prompt": "", "bogus": 1}, t)
        assert "bogus" not in out

    def test_file_type(self):
        t = load_template("robust_video_matting")
        out = hydrate_input({"input_video": "QmSomeCid"}, t)
        assert out["input_video"] == "QmSomeCid"
        with pytest.raises(HydrationError, match="wrong type"):
            hydrate_input({"input_video": 7}, t)


class TestFilters:
    def setup_method(self):
        self.t = load_template("kandinsky2")
        self.base = dict(now=1000.0, fee=100, blocktime=0.0, owner="0x" + "aa" * 20)

    def test_unknown_model(self):
        r = check_model_filter({}, model="0x01", **self.base)
        assert r == FilterResult(False, False, None)

    def test_empty_filters_never_pass(self):
        # reference semantics: default__filters = [] -> filterPassed false
        r = check_model_filter({"0x01": (self.t, [])}, model="0x01", **self.base)
        assert r.model_enabled and not r.filter_passed

    def test_allow_all_filter(self):
        r = check_model_filter({"0x01": (self.t, [MiningFilter()])}, model="0x01", **self.base)
        assert r.filter_passed and r.template is self.t

    def test_minfee(self):
        f = [MiningFilter(minfee=101)]
        assert not check_model_filter({"0x01": (self.t, f)}, model="0x01", **self.base).filter_passed
        f = [MiningFilter(minfee=100)]
        assert check_model_filter({"0x01": (self.t, f)}, model="0x01", **self.base).filter_passed

    def test_mintime(self):
        f = [MiningFilter(mintime=2000)]
        assert not check_model_filter({"0x01": (self.t, f)}, model="0x01", **self.base).filter_passed
        f = [MiningFilter(mintime=500)]
        assert check_model_filter({"0x01": (self.t, f)}, model="0x01", **self.base).filter_passed

    def test_owner_restriction(self):
        f = [MiningFilter(owner="0x" + "bb" * 20)]
        assert not check_model_filter({"0x01": (self.t, f)}, model="0x01", **self.base).filter_passed
        f = [MiningFilter(owner=self.base["owner"])]
        assert check_model_filter({"0x01": (self.t, f)}, model="0x01", **self.base).filter_passed

    def test_first_matching_filter_wins(self):
        f = [MiningFilter(minfee=10**18), MiningFilter()]
        assert check_model_filter({"0x01": (self.t, f)}, model="0x01", **self.base).filter_passed


def test_template_rejects_unknown_types():
    with pytest.raises(ValueError, match="unknown input type"):
        Template.from_dict({"meta": {}, "input": [
            {"variable": "x", "type": "blob"}], "output": []})
    with pytest.raises(ValueError, match="unknown output type"):
        Template.from_dict({"meta": {}, "input": [], "output": [
            {"filename": "f", "type": "hologram"}]})
