"""fleetscope tier-1 suite: percentile estimation, export/merge/render
determinism, sidecar persistence, federation (incl. the dead-gauge NaN
contract), the SLO layer, the coordinator's federated scrape, and the
fleet-mode CLIs. Everything here is unit-speed — the end-to-end halves
(SIM112, the flood SLO report) live in tests/test_sim.py.
"""
import json
import pathlib
import sys

import pytest

from arbius_tpu.node.config import ConfigError, SLOConfig, load_config
from arbius_tpu.obs import Obs
from arbius_tpu.obs.fleetscope import (
    ObsSidecar,
    evaluate_slo,
    federate,
    latency_summary,
    merge_exports,
    merge_journals,
    read_sidecars,
    sidecar_path,
    task_timeline,
)
from arbius_tpu.obs.registry import (
    CHAIN_SECONDS_BUCKETS,
    MetricsRegistry,
    estimate_percentile,
    merge_bucket_counts,
    render_export,
)

REPO = pathlib.Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO / "tools"))


# -- percentile estimation over fixed buckets --------------------------------

def test_estimate_percentile_interpolates_within_bucket():
    edges = (1.0, 2.0, 4.0)
    # 10 samples all landing in the (2, 4] bucket
    counts = [0, 0, 10, 0]
    assert estimate_percentile(edges, counts, 0.5) == pytest.approx(3.0)
    # p0 clamps to the bucket's lower edge, p1 to its upper
    assert estimate_percentile(edges, counts, 0.0) == pytest.approx(2.0)
    assert estimate_percentile(edges, counts, 1.0) == pytest.approx(4.0)


def test_estimate_percentile_empty_and_open_bucket():
    edges = (1.0, 2.0)
    assert estimate_percentile(edges, [0, 0, 0], 0.5) is None
    # mass in the +Inf bucket clamps to the top finite edge
    assert estimate_percentile(edges, [0, 0, 5], 0.99) == 2.0
    with pytest.raises(ValueError, match="\\+Inf"):
        estimate_percentile(edges, [1, 2], 0.5)


def test_histogram_estimate_percentile_not_window_truncated():
    reg = MetricsRegistry()
    h = reg.histogram("h", buckets=(1.0, 10.0), recent_window=4)
    for _ in range(100):
        h.observe(0.5)
    for _ in range(100):
        h.observe(5.0)
    # the recent window only saw the tail; the bucket estimate sees all
    assert h.percentile(0.5) == 5.0
    est = h.estimate_percentile(0.5)
    assert est is not None and est < 2.0
    assert h.bucket_counts() == [100, 100, 0]


def test_histogram_estimate_percentile_edge_cases():
    """Pin the Prometheus-histogram_quantile answers the SLO math
    relies on, per edge case, on the Histogram class itself:

      * empty histogram        → None (no data is not 0.0)
      * all mass in the FIRST bucket → interpolation from 0.0 (the
        implicit lower bound) to the first edge
      * all mass in the +Inf overflow bucket → clamps to the top
        finite edge (never extrapolates past what the edges know)
      * a single observation   → that sample's whole bucket answers
        every quantile (rank 1 of 1 lands there for any q)
    """
    def fresh():
        return MetricsRegistry().histogram("h", buckets=(1.0, 2.0, 4.0))

    # empty
    h = fresh()
    for q in (0.0, 0.5, 0.99, 1.0):
        assert h.estimate_percentile(q) is None
    # all mass in the first bucket: linear from 0.0 up to edge 1.0
    h = fresh()
    for _ in range(10):
        h.observe(0.7)
    assert h.estimate_percentile(0.5) == pytest.approx(0.5)
    assert h.estimate_percentile(1.0) == pytest.approx(1.0)
    assert h.estimate_percentile(0.0) == pytest.approx(0.0)
    # all mass in the overflow bucket: clamp to the top finite edge
    h = fresh()
    for _ in range(7):
        h.observe(100.0)
    for q in (0.01, 0.5, 0.99):
        assert h.estimate_percentile(q) == 4.0
    # single observation: its bucket answers every quantile
    h = fresh()
    h.observe(3.0)  # lands in (2, 4]
    assert h.estimate_percentile(0.0) == pytest.approx(2.0)
    assert h.estimate_percentile(0.5) == pytest.approx(3.0)
    assert h.estimate_percentile(1.0) == pytest.approx(4.0)
    # labeled child with no samples behaves like empty (and must not
    # materialize a series — the _peek contract)
    reg = MetricsRegistry()
    hl = reg.histogram("hl", buckets=(1.0,), labelnames=("stage",))
    assert hl.estimate_percentile(0.5, stage="infer") is None
    assert hl.bucket_counts(stage="infer") == [0, 0]


def test_merge_bucket_counts_rejects_mismatched_edges():
    with pytest.raises(ValueError, match="mismatched bucket edges"):
        merge_bucket_counts((1.0, 2.0), [1, 0, 0],
                            (1.0, 3.0), [1, 0, 0])
    assert merge_bucket_counts((1.0, 2.0), [1, 2, 3],
                               (1.0, 2.0), [4, 5, 6]) == [5, 7, 9]


def test_merging_histogram_exports_with_drifted_edges_fails():
    a, b = MetricsRegistry(), MetricsRegistry()
    a.histogram("arbius_x_seconds", buckets=(1.0, 2.0)).observe(1.5)
    b.histogram("arbius_x_seconds", buckets=(1.0, 4.0)).observe(1.5)
    with pytest.raises(ValueError, match="mismatched bucket edges"):
        merge_exports([("a", a.export()), ("b", b.export())])


def test_latency_summary_deterministic_ordering():
    vals = [3, 1, 500, 40, 40, 7]
    s = latency_summary(vals)
    assert s == latency_summary(sorted(vals))
    assert s["count"] == 6 and s["p50"] <= s["p95"] <= s["p99"]


# -- export / merge / render -------------------------------------------------

def _registry(order_flip: bool, n: int) -> MetricsRegistry:
    reg = MetricsRegistry()
    names = ["arbius_b_total", "arbius_a_total"]
    if order_flip:
        names.reverse()
    for name in names:
        reg.counter(name, "help text").inc(n)
    g = reg.gauge("arbius_depth", "d", labelnames=("stage",))
    g.set(n, stage="encode")
    h = reg.histogram("arbius_lat_seconds", "l",
                      buckets=CHAIN_SECONDS_BUCKETS)
    for v in (1, 30, 600):
        h.observe(v * n)
    return reg


def test_merge_and_render_byte_identical_in_any_order():
    a, b = _registry(False, 1), _registry(True, 3)
    ab = render_export(merge_exports([("a", a.export()),
                                      ("b", b.export())]))
    ba = render_export(merge_exports([("b", b.export()),
                                      ("a", a.export())]))
    assert ab == ba
    assert "arbius_a_total 4" in ab and "arbius_b_total 4" in ab
    assert 'arbius_depth{stage="encode"} 4' in ab
    # merged histogram: bucket counts summed (6 observations total)
    assert "arbius_lat_seconds_count 6" in ab


def test_render_export_matches_local_render_bytes():
    reg = _registry(False, 2)
    assert render_export(reg.export()) == reg.render()


def test_shape_conflict_across_members_is_an_error():
    a, b = MetricsRegistry(), MetricsRegistry()
    a.counter("arbius_x_total").inc()
    b.gauge("arbius_x_total").set(1)
    with pytest.raises(ValueError, match="different shapes|kind"):
        merge_exports([("a", a.export()), ("b", b.export())])


def test_dead_labeled_gauge_nan_propagates_through_federation():
    """A labeled callback gauge whose source died in ONE member must
    surface as `name NaN` in the FEDERATED exposition too — an
    unreachable lease table must never scrape fleet-wide as 'fully
    drained' (the PR 9 dead-source contract, lifted to the fleet)."""
    alive, dead = MetricsRegistry(), MetricsRegistry()
    alive.gauge("arbius_fleet_leases", labelnames=("state",),
                fn=lambda: {"pending": 3})
    def boom():
        raise RuntimeError("lease table gone")
    dead.gauge("arbius_fleet_leases", labelnames=("state",), fn=boom)
    text = render_export(merge_exports([("a", alive.export()),
                                        ("b", dead.export())]))
    assert "arbius_fleet_leases NaN" in text
    # an unlabeled summed gauge propagates NaN arithmetically
    alive2, dead2 = MetricsRegistry(), MetricsRegistry()
    alive2.gauge("arbius_queue_depth", fn=lambda: 4)
    dead2.gauge("arbius_queue_depth", fn=boom)
    text2 = render_export(merge_exports([("a", alive2.export()),
                                         ("b", dead2.export())]))
    assert "arbius_queue_depth NaN" in text2


# -- sidecars + federation ---------------------------------------------------

def _member_obs(n: int) -> Obs:
    obs = Obs(now_fn=lambda: 100 + n)
    obs.registry.counter("arbius_tasks_seen_total", "seen").inc(n)
    obs.journal.record("lease_hop", taskid="0xt1", worker=f"worker-{n}",
                       hop=n, op="acquire")
    return obs


def test_sidecar_roundtrip_and_federation(tmp_path):
    for i in (1, 2):
        obs = _member_obs(i)
        sc = ObsSidecar(sidecar_path(str(tmp_path), f"worker-{i}"),
                        f"worker-{i}", obs)
        assert sc.flush(now=100 + i) == 1
        # idempotent re-flush: same seqs are INSERT OR IGNOREd
        assert sc.flush(now=100 + i) == 0
        sc.close()
    members = read_sidecars(str(tmp_path))
    assert [m for m, _, _ in members] == ["worker-1", "worker-2"]
    view = federate(str(tmp_path))
    assert view["members"] == ["worker-1", "worker-2"]
    text = render_export(view["export"])
    assert "arbius_tasks_seen_total 3" in text
    # sidecar flushes counted (and documented — OBS501)
    assert "arbius_obs_sidecar_flushes_total" in text
    # merged timeline: ordered by (chain, member, seq), member-tagged
    tl = task_timeline(view["events"], "0xt1")
    assert [e["member"] for e in tl] == ["worker-1", "worker-2"]
    assert [e["chain"] for e in tl] == [101, 102]


def test_sidecar_journal_retention_bounds_the_file(tmp_path):
    """The sidecar is a flight recorder, not an archive: journal rows
    beyond `journal_retention` are pruned at flush, so a long-running
    member's .obs.sqlite stays bounded."""
    import sqlite3

    obs = Obs()
    sc = ObsSidecar(sidecar_path(str(tmp_path), "w"), "w", obs,
                    journal_retention=5)
    for i in range(12):
        obs.journal.record("tickmark", i=i)
        if i % 4 == 3:
            sc.flush(now=i)
    sc.close()
    conn = sqlite3.connect(sidecar_path(str(tmp_path), "w"))
    seqs = [r[0] for r in conn.execute(
        "SELECT seq FROM journal ORDER BY seq")]
    conn.close()
    assert len(seqs) == 5 and seqs == list(range(8, 13))


def test_sidecar_restart_clears_dead_lifes_journal(tmp_path):
    """A restarted production member reuses its sidecar path with a
    FRESH journal whose seqs restart at 1: the dead life's rows (whose
    seqs ran ahead) must be cleared at open, or INSERT OR IGNORE would
    freeze the sidecar at the old life's events forever."""
    path = sidecar_path(str(tmp_path), "w")
    life1 = Obs()
    for i in range(5):
        life1.journal.record("old_life", i=i)
    sc = ObsSidecar(path, "w", life1)
    sc.flush(now=10)
    sc.close()
    life2 = Obs()
    life2.journal.record("new_life")
    sc = ObsSidecar(path, "w", life2)
    sc.flush(now=20)
    sc.close()
    _, _, events = read_sidecars(str(tmp_path))[0]
    assert [e["kind"] for e in events] == ["new_life"]


def test_merge_rejects_drifted_edges_on_disjoint_label_series():
    """A member contributing only NEW label series must not smuggle a
    drifted edge set past the per-series merge — edge compatibility is
    per metric."""
    a, b = MetricsRegistry(), MetricsRegistry()
    a.histogram("arbius_x_seconds", buckets=(1.0, 2.0),
                labelnames=("stage",)).observe(1.5, stage="infer")
    b.histogram("arbius_x_seconds", buckets=(1.0, 4.0),
                labelnames=("stage",)).observe(1.5, stage="decode")
    with pytest.raises(ValueError, match="mismatched bucket edges"):
        merge_exports([("a", a.export()), ("b", b.export())])


def test_merge_journals_orders_by_chain_time():
    a = [{"kind": "x", "seq": 1, "chain": 50}]
    b = [{"kind": "y", "seq": 1, "chain": 10},
         {"kind": "z", "seq": 2, "chain": 50}]
    merged = merge_journals([("b", b), ("a", a)])
    assert [(e["member"], e["kind"]) for e in merged] == \
        [("b", "y"), ("a", "x"), ("b", "z")]


def test_fleet_metrics_server_serves_federated_view(tmp_path):
    import urllib.request

    from arbius_tpu.obs.fleetscope import FleetMetricsServer

    obs = _member_obs(5)
    sc = ObsSidecar(sidecar_path(str(tmp_path), "worker-5"),
                    "worker-5", obs)
    sc.flush(now=105)
    sc.close()
    coord = Obs()
    coord.registry.counter("arbius_fleet_tasks_total", "dealt").inc(7)
    # the coordinator ALSO flushes its own sidecar into the same dir
    # (the production wiring): the live registry must supersede that
    # stale snapshot, never sum with it
    csc = ObsSidecar(sidecar_path(str(tmp_path), "coordinator"),
                     "coordinator", coord)
    csc.flush(now=100)
    csc.close()
    coord.registry.counter("arbius_fleet_tasks_total").inc(2)  # now 9
    server = FleetMetricsServer(str(tmp_path), coord)
    server.start()
    try:
        url = f"http://127.0.0.1:{server.port}/metrics"
        with urllib.request.urlopen(url, timeout=10) as r:
            body = r.read().decode()
            assert "version=0.0.4" in r.headers["Content-Type"]
        assert "arbius_tasks_seen_total 5" in body
        # live 9, NOT live+sidecar 16 (and not the stale 7)
        assert "arbius_fleet_tasks_total 9" in body
        with pytest.raises(urllib.error.HTTPError):
            urllib.request.urlopen(
                f"http://127.0.0.1:{server.port}/nope", timeout=10)
    finally:
        server.stop()


# -- SLO config + evaluation -------------------------------------------------

def test_slo_config_validation():
    with pytest.raises(ConfigError, match="queue_wait_p95"):
        SLOConfig(queue_wait_p95=-1)
    with pytest.raises(ConfigError, match="chip_idle_fraction"):
        SLOConfig(chip_idle_fraction=1.5)
    cfg = load_config(json.dumps({"slo": {"time_to_commit_p99": 120}}))
    assert cfg.slo.time_to_commit_p99 == 120
    with pytest.raises(ConfigError, match="slo"):
        load_config(json.dumps({"slo": {"bogus": 1}}))
    with pytest.raises(ConfigError, match="sidecar_flush_every"):
        load_config(json.dumps({"fleet": {"sidecar_flush_every": 0}}))


def test_evaluate_slo_breaches_and_holds():
    report = {
        "queue_wait_seconds": {"count": 10, "p50": 1, "p95": 9,
                               "p99": 20},
        "time_to_commit_seconds": {"count": 10, "p50": 5, "p95": 50,
                                   "p99": 90},
        "steal_lag_seconds": {"count": 0, "p50": None, "p95": None,
                              "p99": None},
        "chip_idle_fraction": 0.4,
    }
    assert evaluate_slo(SLOConfig(), report) == []
    breaches = evaluate_slo(
        SLOConfig(queue_wait_p95=5, time_to_commit_p99=100,
                  steal_lag_p99=1, chip_idle_fraction=0.3), report)
    assert len(breaches) == 2
    assert any("queue_wait_seconds p95" in b for b in breaches)
    assert any("chip_idle_fraction" in b for b in breaches)
    # empty percentiles (no traffic) never breach — liveness is SIM108
    assert not evaluate_slo(SLOConfig(steal_lag_p99=0.1), report)


# -- the fleet-mode CLIs -----------------------------------------------------

@pytest.fixture()
def sidecar_dir(tmp_path):
    for i in (1, 2):
        obs = _member_obs(i)
        obs.registry.histogram(
            "arbius_fleet_queue_wait_seconds", "qw",
            buckets=CHAIN_SECONDS_BUCKETS).observe(4 * i, tag="0xt1")
        sc = ObsSidecar(sidecar_path(str(tmp_path), f"worker-{i}"),
                        f"worker-{i}", obs)
        sc.flush(now=100 + i)
        sc.close()
    return tmp_path


def test_fleetscope_cli_prom_and_slo(sidecar_dir, capsys):
    from fleetscope import main as fs_main

    assert fs_main([str(sidecar_dir), "prom"]) == 0
    out = capsys.readouterr().out
    assert "arbius_tasks_seen_total 3" in out
    assert "arbius_fleet_queue_wait_seconds_count 2" in out
    # slo: clean without thresholds, exit 1 on a declared breach
    assert fs_main([str(sidecar_dir), "slo"]) == 0
    capsys.readouterr()
    assert fs_main([str(sidecar_dir), "slo",
                    "--queue-wait-p95", "0.5"]) == 1
    out = capsys.readouterr().out
    assert "SLO101" in out and "queue_wait_seconds p95" in out


def test_fleetscope_cli_timeline(sidecar_dir, capsys):
    from fleetscope import main as fs_main

    assert fs_main([str(sidecar_dir), "timeline",
                    "--taskid", "0xt1"]) == 0
    out = capsys.readouterr().out
    assert "worker-1" in out and "worker-2" in out
    assert "lease_hop" in out
    # --limit 0 means "no events", not "all of them" ([-0:] trap)
    assert fs_main([str(sidecar_dir), "timeline", "--limit", "0"]) == 0
    assert capsys.readouterr().out.strip() == ""


def test_corrupt_sidecar_is_a_usage_error_not_a_traceback(tmp_path,
                                                          capsys):
    """A member killed mid-creation leaves a garbage .obs.sqlite: the
    reader raises ValueError naming the file (which the CLIs turn into
    exit 2) and the federated metrics server answers a diagnosable 500
    — one bad member must never crash the whole-fleet view."""
    import urllib.request

    from fleetscope import main as fs_main

    from arbius_tpu.obs.fleetscope import FleetMetricsServer

    bad = tmp_path / ("worker-9" + ".obs.sqlite")
    bad.write_bytes(b"not a sqlite file at all")
    with pytest.raises(ValueError, match="unreadable obs sidecar"):
        read_sidecars(str(tmp_path))
    assert fs_main([str(tmp_path), "prom"]) == 2
    assert "unreadable obs sidecar" in capsys.readouterr().err
    server = FleetMetricsServer(str(tmp_path))
    server.start()
    try:
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(
                f"http://127.0.0.1:{server.port}/metrics", timeout=10)
        assert ei.value.code == 500
        assert b"unreadable obs sidecar" in ei.value.read()
    finally:
        server.stop()


def test_obs_dump_fleet_mode(sidecar_dir, capsys):
    from obs_dump import main as od_main

    assert od_main(["--fleet", str(sidecar_dir), "prom"]) == 0
    assert "arbius_tasks_seen_total 3" in capsys.readouterr().out
    assert od_main(["--fleet", str(sidecar_dir), "journal"]) == 0
    out = capsys.readouterr().out
    assert "lease_hop" in out and "worker-2" in out
    assert od_main(["--fleet", str(sidecar_dir), "trace", "0xt1"]) == 0
    assert od_main(["--fleet", str(sidecar_dir), "trace", "0xnope"]) == 1


# -- lease-table hop chain (the shared-truth half of SIM112) -----------------

def test_lease_hops_record_deal_acquire_steal_reclaim(tmp_path):
    from arbius_tpu.fleet import LeaseTable
    from arbius_tpu.obs import use_obs

    obs = Obs()
    table = LeaseTable(str(tmp_path / "leases.sqlite"))
    with use_obs(obs):
        table.add_task("0xt", "0xm", 5, 100, 100)
        grants = table.acquire("worker-0", now=110, ttl=10, limit=5)
        assert [g.hop for g in grants] == [1]
        # worker-0 goes dark; worker-1 steals past the TTL
        stolen = table.acquire("worker-1", now=130, ttl=10, limit=5)
        assert stolen[0].stolen and stolen[0].hop == 2
        table.reclaim(now=150, max_attempts=4)
    # steal lag observed on BOTH takeover paths (the slo.steal_lag_p99
    # corpus): worker steal at 130 (lag 10) + coordinator reclaim at
    # 150 (lag 10)
    lag_h = obs.registry.get("arbius_fleet_steal_lag_seconds")
    assert lag_h.count() == 2 and [v for _, v in lag_h.recent()] == \
        [10, 10]
    row = dict(table.rows()[0])
    hops = json.loads(row["hops"])
    assert [h["hop"] for h in hops] == [0, 1, 2, 3]
    assert [h["op"] for h in hops] == ["deal", "acquire", "steal",
                                      "reclaim"]
    assert hops[2]["worker"] == "worker-1" and hops[2]["lag"] == 10
    assert hops[3]["lag"] == 10
    # queue wait observed on the FIRST acquire only, chain buckets
    h = obs.registry.get("arbius_fleet_queue_wait_seconds")
    assert h.count() == 1 and h.recent() == [("0xt", 10)]
    assert h.buckets == tuple(CHAIN_SECONDS_BUCKETS)
    table.close()


def test_lease_hops_migration_adds_column(tmp_path):
    """A pre-fleetscope lease db (no hops column) opens and migrates in
    place — the shared file may outlive any one member's version."""
    import sqlite3

    from arbius_tpu.fleet import LeaseTable

    path = str(tmp_path / "old.sqlite")
    conn = sqlite3.connect(path)
    conn.execute(
        "CREATE TABLE leases (id INTEGER PRIMARY KEY AUTOINCREMENT,"
        " taskid TEXT UNIQUE, model TEXT, fee TEXT, blocktime INT,"
        " state TEXT, worker TEXT DEFAULT '', expires INT DEFAULT 0,"
        " acquired INT DEFAULT 0, attempts INT DEFAULT 0,"
        " steals INT DEFAULT 0)")
    conn.execute("INSERT INTO leases (taskid, model, fee, blocktime,"
                 " state) VALUES ('0xold', '0xm', '1', 50, 'pending')")
    conn.commit()
    conn.close()
    table = LeaseTable(path)
    grants = table.acquire("worker-0", now=60, ttl=10, limit=5)
    assert grants[0].taskid == "0xold" and grants[0].hop == 0
    hops = json.loads(dict(table.rows()[0])["hops"])
    assert [h["op"] for h in hops] == ["acquire"]
    table.close()
