"""solvepipe — the staged solve executor (arbius_tpu/node/pipeline.py).

The load-bearing property is BYTE EQUALITY: solution files and CIDs must
be identical pipeline-on vs pipeline-off for every runner family the
fakes cover (SD15-shaped dispatch/finalize runners and RVM-shaped plain
callables), at canonical_batch 1 and 4 — the pipeline may only change
the schedule, never the bytes. The simnet crash-mid-pipeline test proves
restart-from-checkpoint loses no task and never double-commits.
"""
from __future__ import annotations

import json

import pytest

from arbius_tpu.chain import Engine, TokenLedger, WAD
from arbius_tpu.l0.cid import cid_hex, cid_of_solution_files
from arbius_tpu.node import (
    LocalChain,
    MinerNode,
    MiningConfig,
    ModelConfig,
    ModelRegistry,
    RegisteredModel,
)
from arbius_tpu.node.config import ConfigError, PipelineConfig, load_config
from arbius_tpu.templates.engine import load_template
from tests.test_node import MINER, MODEL_ADDR, USER, drain, submit, task_input

PIPE_ON = PipelineConfig(enabled=True, depth=2, encode_workers=2,
                         max_inflight_pins=2)


class _RecordingPinner:
    """Captures the exact bytes every task pinned (the byte-equality
    oracle) while answering like a well-behaved service."""

    def __init__(self):
        self.pinned: dict[str, dict] = {}

    def pin_files(self, files: dict, taskid: str = "") -> bytes:
        self.pinned[taskid] = dict(files)
        return cid_of_solution_files(files)

    def pin_blob(self, content: bytes, filename: str = "input") -> bytes:
        from arbius_tpu.l0.cid import dag_of_file

        return dag_of_file(content).cid


class _SD15FakeRunner:
    """SD15Runner-shaped: dispatch/finalize split, run_batch, callable —
    deterministic PNG-ish bytes from (input, seed). Logs the schedule so
    tests can assert the overlap actually happened."""

    def __init__(self, log=None):
        self.log = log if log is not None else []

    def __call__(self, hydrated, seed):
        return self.finalize(self.dispatch([(hydrated, seed)]), 1)[0]

    def run_batch(self, items):
        return self.finalize(self.dispatch(items), len(items))

    def dispatch(self, items):
        self.log.append(("dispatch", len(items)))
        return [self._bytes(h, s) for h, s in items]

    def finalize(self, dev, n_real):
        self.log.append(("finalize", n_real))
        return [{"out-1.png": dev[i]} for i in range(n_real)]

    @staticmethod
    def _bytes(hydrated, seed):
        blob = json.dumps({k: v for k, v in sorted(hydrated.items())
                           if k != "seed"}).encode()
        return b"\x89PNG" + blob + seed.to_bytes(8, "big")


class _RVMFakeRunner:
    """RVMRunner-shaped: a plain callable with NO batch/dispatch
    surface, seed-independent like the real matting model (the runner
    interface family is what's under test; the declared output name
    follows the test template)."""

    def __call__(self, hydrated, seed):
        blob = json.dumps({k: v for k, v in sorted(hydrated.items())
                           if k != "seed"}).encode()
        return {"out-1.png": b"\x00\x00\x00 ftypisom" + blob}


def _world(runner, *, pipeline=None, canonical_batch=1):
    tok = TokenLedger()
    eng = Engine(tok, start_time=10_000)
    tok.mint(Engine.ADDRESS, 600_000 * WAD)
    for a in (MINER, USER):
        tok.mint(a, 1_000 * WAD)
        tok.approve(a, Engine.ADDRESS, 10**30)
    mid_b = eng.register_model(USER, MODEL_ADDR, 0, b'{"meta":{}}')
    mid = "0x" + mid_b.hex()
    registry = ModelRegistry()
    registry.register(RegisteredModel(
        id=mid, template=load_template("anythingv3"), runner=runner))
    chain = LocalChain(eng, MINER)
    chain.validator_deposit(100 * WAD)
    cfg = MiningConfig(
        models=(ModelConfig(id=mid, template="anythingv3"),),
        canonical_batch=canonical_batch,
        pipeline=pipeline or PipelineConfig())
    pinner = _RecordingPinner()
    node = MinerNode(chain, cfg, registry, pinner=pinner)
    node.boot()
    drain(node)
    return eng, node, mid, pinner


def _mine(runner_cls, *, pipeline, canonical_batch, n_tasks=5):
    """Drive n_tasks through one world; returns {taskid: (cid, files)}."""
    eng, node, mid, pinner = _world(runner_cls(), pipeline=pipeline,
                                    canonical_batch=canonical_batch)
    tids = [submit(eng, mid, prompt=f"task {i}") for i in range(n_tasks)]
    drain(node)
    out = {}
    for tid in tids:
        sol = eng.solutions[bytes.fromhex(tid[2:])]
        out[tid] = ("0x" + sol.cid.hex(), pinner.pinned.get(tid))
    node.close()
    return out


# -- byte equality: the golden acceptance gate ------------------------------

@pytest.mark.parametrize("batch", [1, 4])
@pytest.mark.parametrize("runner_cls", [_SD15FakeRunner, _RVMFakeRunner])
def test_cids_and_bytes_identical_pipeline_on_vs_off(runner_cls, batch):
    off = _mine(runner_cls, pipeline=None, canonical_batch=batch)
    on = _mine(runner_cls, pipeline=PIPE_ON, canonical_batch=batch)
    # identical chain writes are impossible across two engines, but the
    # task ids are (same submitter nonce chain) — compare directly
    assert off.keys() == on.keys()
    for tid in off:
        cid_off, files_off = off[tid]
        cid_on, files_on = on[tid]
        assert cid_off == cid_on, f"CID drift for {tid}"
        assert files_off == files_on, f"byte drift for {tid}"
        # and the CID really is the hash of the pinned bytes
        assert cid_on == cid_hex(cid_of_solution_files(files_on))


def test_inline_encode_mode_matches_too():
    """encode_workers=0 keeps everything on the tick thread (no pool);
    bytes still identical, chip overlap still via async dispatch."""
    inline = PipelineConfig(enabled=True, depth=3, encode_workers=0,
                            max_inflight_pins=1)
    off = _mine(_SD15FakeRunner, pipeline=None, canonical_batch=4)
    on = _mine(_SD15FakeRunner, pipeline=inline, canonical_batch=4)
    assert off == on


# -- schedule: the chip window actually fills ------------------------------

def test_depth_k_prefetch_dispatches_ahead():
    log = []
    eng, node, mid, _ = _world(
        _SD15FakeRunner(log), canonical_batch=2,
        pipeline=PipelineConfig(enabled=True, depth=2, encode_workers=0,
                                max_inflight_pins=8))
    for i in range(6):
        submit(eng, mid, prompt=f"t{i}")
    log.clear()
    drain(node)
    kinds = [k for k, _ in log]
    # 3 chunks, window 2: the second dispatch precedes the first
    # finalize, and the window refills before the second finalize
    assert kinds == ["dispatch", "dispatch", "finalize", "dispatch",
                     "finalize", "finalize"]
    node.close()


def test_pipeline_stage_events_are_monotonic_per_task():
    eng, node, mid, _ = _world(_SD15FakeRunner(), canonical_batch=2,
                               pipeline=PIPE_ON)
    tids = [submit(eng, mid, prompt=f"t{i}") for i in range(4)]
    drain(node)
    from arbius_tpu.node.pipeline import STAGE_RANK

    for tid in tids:
        evs = node.obs.journal.events(kind="pipeline_stage", taskid=tid)
        stages = [e["stage"] for e in evs]
        assert stages == ["solve", "encode", "pin", "commit", "reveal"]
        ranks = [STAGE_RANK[s] for s in stages]
        assert ranks == sorted(ranks)
    node.close()


def test_pipeline_metrics_registered_and_moving():
    eng, node, mid, _ = _world(_SD15FakeRunner(), canonical_batch=2,
                               pipeline=PIPE_ON)
    for i in range(4):
        submit(eng, mid, prompt=f"t{i}")
    drain(node)
    reg = node.obs.registry
    h = reg.histogram("arbius_pipeline_stage_seconds",
                      labelnames=("stage",))
    assert h.count(stage="device") >= 2
    assert h.count(stage="encode") >= 2
    assert h.count(stage="network") == 4
    # the profitability gate's infer signal stays live in pipeline mode
    # at the SERIAL path's granularity: one sample per bucket, so the
    # p50 cost estimate reads the same whichever schedule runs
    assert len(node.metrics.stage_seconds["infer"]) == 1
    assert reg.counter("arbius_chip_idle_seconds_total").value() >= 0.0
    node.close()


# -- db write batching (one tick = one fsync) -------------------------------

def test_tick_batches_sqlite_commits_to_one():
    """A tick's claim/delete cycle used to fsync per mutation; under
    NodeDB.batch() the whole tick is ONE commit, and the obs counter +
    histogram record the win."""
    eng, node, mid, _ = _world(_SD15FakeRunner(), canonical_batch=1)
    reg = node.obs.registry
    for i in range(4):
        submit(eng, mid, prompt=f"t{i}")
    c = reg.counter("arbius_db_commits_total")
    h = reg.histogram("arbius_db_commit_seconds")
    before, hbefore = c.value(), h.count()
    done = node.tick()   # 4 task jobs: store input + queue solve + delete
    assert done == 4
    assert c.value() - before == 1, "a tick must be exactly one fsync"
    assert h.count() - hbefore == 1
    node.close()


# -- failure isolation ------------------------------------------------------

def test_chunk_failure_quarantines_only_that_chunk():
    class FlakyRunner(_SD15FakeRunner):
        def dispatch(self, items):
            if any(h["prompt"] == "boom" for h, _ in items):
                raise RuntimeError("chunk exploded")
            return super().dispatch(items)

    eng, node, mid, _ = _world(FlakyRunner(), canonical_batch=1,
                               pipeline=PIPE_ON)
    good = [submit(eng, mid, prompt=f"ok {i}") for i in range(2)]
    bad = submit(eng, mid, prompt="boom")
    drain(node)
    for tid in good:
        assert bytes.fromhex(tid[2:]) in eng.solutions
    assert bytes.fromhex(bad[2:]) not in eng.solutions
    assert ("solve", {"taskid": bad, "model": mid}) in [
        (m, d) for m, d in node.db.failed_jobs()]
    node.close()


def test_kill_class_death_in_encode_worker_surfaces_as_failure():
    """A BaseException inside a worker's finalize must not silently
    kill the thread before it posts a result — that would wedge the
    tick thread in cv.wait forever. It surfaces as a quarantined chunk
    instead."""
    class DyingRunner(_SD15FakeRunner):
        def finalize(self, dev, n_real):
            raise KeyboardInterrupt("worker killed")

    eng, node, mid, _ = _world(DyingRunner(), canonical_batch=2,
                               pipeline=PIPE_ON)
    tids = [submit(eng, mid, prompt=f"t{i}") for i in range(2)]
    drain(node)   # must return, not hang
    failed = {d.get("taskid") for m, d in node.db.failed_jobs()
              if m == "solve"}
    assert failed == set(tids)
    node.close()


# -- checkpoint resume ------------------------------------------------------

class _CountingPinner(_RecordingPinner):
    def __init__(self):
        super().__init__()
        self.calls = 0

    def pin_files(self, files, taskid=""):
        self.calls += 1
        return super().pin_files(files, taskid=taskid)


def _crash_world(tmp_path):
    """Shared fixture for the two crash flavors: a durable-checkpoint
    world builder plus a kill planted inside signal_commitment."""
    db_path = str(tmp_path / "node.sqlite")
    tok = TokenLedger()
    eng = Engine(tok, start_time=10_000)
    tok.mint(Engine.ADDRESS, 600_000 * WAD)
    for a in (MINER, USER):
        tok.mint(a, 1_000 * WAD)
        tok.approve(a, Engine.ADDRESS, 10**30)
    mid = "0x" + eng.register_model(USER, MODEL_ADDR, 0, b"{}").hex()
    registry = ModelRegistry()
    registry.register(RegisteredModel(
        id=mid, template=load_template("anythingv3"),
        runner=_SD15FakeRunner()))

    def spawn(pinner):
        chain = LocalChain(eng, MINER)
        cfg = MiningConfig(db_path=db_path,
                           models=(ModelConfig(id=mid,
                                               template="anythingv3"),),
                           pipeline=PIPE_ON)
        node = MinerNode(chain, cfg, registry, pinner=pinner)
        node.boot()
        return node

    chain0 = LocalChain(eng, MINER)
    chain0.validator_deposit(100 * WAD)
    return eng, mid, spawn


def test_pipeline_resumes_pin_recorded_by_a_flushed_window(tmp_path):
    """A pin the checkpoint DURABLY recorded before a crash is not
    re-run by the next life. The tick's batch window is made durable
    the way it happens in production: a foreign (ControlRPC-class)
    thread writes mid-tick, which fsyncs the window so far."""
    import threading

    eng, mid, spawn = _crash_world(tmp_path)
    p1 = _CountingPinner()
    node = spawn(p1)
    drain(node)
    tid = submit(eng, mid)

    def flush_then_die(_commitment):
        t = threading.Thread(target=lambda: node.db.queue_job(
            "voteFinish", {"taskid": "0xflush"}, waituntil=2**50))
        t.start()
        t.join()
        raise KeyboardInterrupt("sim kill")

    node.chain.signal_commitment = flush_then_die
    with pytest.raises(KeyboardInterrupt):
        drain(node)
    assert p1.calls == 1
    state = node.db.get_pipeline_stage(tid)
    assert state is not None and state[0] == "pin"
    assert state[1] == cid_hex(cid_of_solution_files(p1.pinned[tid]))
    node.close()

    # reboot from the same checkpoint: solve re-runs, pin is skipped
    p2 = _CountingPinner()
    node2 = spawn(p2)
    drain(node2)
    assert p2.calls == 0, "restart re-ran a pin the checkpoint recorded"
    assert bytes.fromhex(tid[2:]) in eng.solutions
    resumed = [e for e in node2.obs.journal.events(kind="pipeline_stage",
                                                   taskid=tid)
               if e.get("resumed")]
    assert [e["stage"] for e in resumed] == ["pin"]
    # stage row cleared once the task completed
    assert node2.db.get_pipeline_stage(tid) is None
    node2.close()


def test_pipeline_lost_batch_window_still_converges(tmp_path):
    """kill -9 semantics: a BaseException unwinding the tick loses the
    whole deferred sqlite window (batch() deliberately does NOT commit
    on the process-death class), so the rebooted node finds NO
    pipeline_state row — it must redo the pin and still converge to the
    same CID with a single commitment."""
    eng, mid, spawn = _crash_world(tmp_path)
    p1 = _CountingPinner()
    node = spawn(p1)
    drain(node)
    tid = submit(eng, mid)
    node.chain.signal_commitment = lambda c: (_ for _ in ()).throw(
        KeyboardInterrupt("sim kill"))
    with pytest.raises(KeyboardInterrupt):
        drain(node)
    assert p1.calls == 1
    # the window died with the process: nothing was checkpointed
    assert node.db.get_pipeline_stage(tid) is None
    node.close()

    p2 = _CountingPinner()
    node2 = spawn(p2)
    drain(node2)
    assert p2.calls == 1, "lost window must be re-derived, incl. the pin"
    sol = eng.solutions[bytes.fromhex(tid[2:])]
    assert "0x" + sol.cid.hex() == cid_hex(
        cid_of_solution_files(p2.pinned[tid]))
    assert p2.pinned[tid] == p1.pinned[tid], "re-derived bytes drifted"
    node2.close()


# -- config surface ---------------------------------------------------------

def test_pipeline_config_loads_and_validates():
    cfg = load_config({"pipeline": {"enabled": True, "depth": 3,
                                    "encode_workers": 2,
                                    "max_inflight_pins": 8}})
    assert cfg.pipeline.enabled and cfg.pipeline.depth == 3
    assert not load_config({}).pipeline.enabled  # default: synchronous
    with pytest.raises(ConfigError, match="depth"):
        load_config({"pipeline": {"depth": 0}})
    with pytest.raises(ConfigError, match="encode_workers"):
        load_config({"pipeline": {"encode_workers": -1}})
    with pytest.raises(ConfigError, match="max_inflight_pins"):
        load_config({"pipeline": {"max_inflight_pins": 0}})


# -- simnet: crash mid-pipeline ---------------------------------------------

def test_simnet_crash_mid_pipeline_loses_nothing(tmp_path):
    """Kill the node after its 2nd commit lands (mid-pipeline, with the
    staged executor active), reboot from the checkpoint: every task
    claimed, no double-commit, SIM101-109 all green."""
    from arbius_tpu.sim.harness import run_scenario
    from arbius_tpu.sim.invariants import check_all, classify_tasks
    from arbius_tpu.sim.scenario import get_scenario

    result = run_scenario(get_scenario("crash-restart"), 3,
                          db_path=str(tmp_path / "crash.sqlite"))
    assert result.pipeline_enabled
    findings = check_all(result)
    assert not findings, "\n".join(f.text() for f in findings)
    assert result.restarts == 1
    assert set(classify_tasks(result).values()) == {"claimed"}
    # no (validator, task) pair ever committed two different CIDs
    per_task: dict[str, set] = {}
    for sender, tid, cid in result.plane.commitments.values():
        if sender == result.miner_address:
            per_task.setdefault(tid, set()).add(cid)
    assert per_task and all(len(c) == 1 for c in per_task.values())
