"""Node ↔ real model-family integration: each template class solves through
the full event→solve→commit→reveal loop with its actual (tiny-config)
pipeline — kandinsky2 (PNG), zeroscope-class video (MP4), RVM (file input
→ MP4). The SD-1.5 path is covered by the /verify drive and bench.
"""
from __future__ import annotations

import json

import numpy as np
import pytest

from arbius_tpu.chain import Engine, TokenLedger, WAD
from arbius_tpu.codecs import encode_mp4
from arbius_tpu.codecs.mp4_demux import decode_mjpeg_mp4
from arbius_tpu.models.kandinsky2 import Kandinsky2Config, Kandinsky2Pipeline
from arbius_tpu.models.rvm import RVMPipeline, RVMPipelineConfig
from arbius_tpu.models.sd15 import ByteTokenizer
from arbius_tpu.models.video import Text2VideoConfig, Text2VideoPipeline
from arbius_tpu.node import (
    Kandinsky2Runner,
    LocalChain,
    MinerNode,
    MiningConfig,
    ModelConfig,
    ModelRegistry,
    RVMRunner,
    RegisteredModel,
    Text2VideoRunner,
)
from arbius_tpu.templates.engine import load_template

pytestmark = [pytest.mark.slow, pytest.mark.model]

MINER = "0x" + "aa" * 20
USER = "0x" + "01" * 20


def tok():
    return ByteTokenizer(max_length=16, bos_id=257, eos_id=258)


def world(template_name, runner):
    tokl = TokenLedger()
    eng = Engine(tokl, start_time=10_000)
    tokl.mint(Engine.ADDRESS, 600_000 * WAD)
    for a in (MINER, USER):
        tokl.mint(a, 1000 * WAD)
        tokl.approve(a, Engine.ADDRESS, 10**30)
    mid_b = eng.register_model(USER, USER, 0, b'{"meta":{"title":"m"}}')
    mid = "0x" + mid_b.hex()
    reg = ModelRegistry()
    reg.register(RegisteredModel(id=mid,
                                 template=load_template(template_name),
                                 runner=runner))
    chain = LocalChain(eng, MINER)
    chain.validator_deposit(100 * WAD)
    node = MinerNode(
        chain, MiningConfig(models=(ModelConfig(id=mid,
                                                template=template_name),)),
        reg)
    node.boot()
    while node.tick():
        pass
    return eng, node, mid_b


def drain(node):
    while node.tick():
        pass


def test_kandinsky2_node_enforces_template_enum():
    """The kandinsky2 template pins w/h to {768, 1024}; an off-enum task
    is marked invalid and never solved (hydrateInput parity). The full
    768² solve is too slow for CI — the runner itself is covered at 64²
    by test_kandinsky2_runner_direct."""
    pipe = Kandinsky2Pipeline(Kandinsky2Config.tiny(), tokenizer=tok())
    runner = Kandinsky2Runner(pipe, pipe.init_params(seed=0))
    eng, node, mid_b = world("kandinsky2", runner)
    tid = eng.submit_task(USER, 0, USER, mid_b, 0, json.dumps(
        {"prompt": "arbius test cat", "width": 64, "height": 64}).encode())
    drain(node)
    assert node.db.is_invalid_task("0x" + tid.hex())
    assert tid not in eng.solutions


def test_kandinsky2_runner_direct():
    pipe = Kandinsky2Pipeline(Kandinsky2Config.tiny(), tokenizer=tok())
    runner = Kandinsky2Runner(pipe, pipe.init_params(seed=0))
    files = runner({"prompt": "cat", "width": 64, "height": 64,
                    "num_inference_steps": 2}, 1337)
    assert set(files) == {"out-1.png"}
    assert files["out-1.png"][:8] == b"\x89PNG\r\n\x1a\n"
    again = runner({"prompt": "cat", "width": 64, "height": 64,
                    "num_inference_steps": 2}, 1337)
    assert files == again


def test_zeroscope_class_through_node():
    pipe = Text2VideoPipeline(Text2VideoConfig.tiny(), tokenizer=tok())
    runner = Text2VideoRunner(
        pipe, pipe.init_params(seed=0),
        defaults={"num_frames": 2, "width": 64, "height": 64,
                  "num_inference_steps": 2})
    eng, node, mid_b = world("zeroscopev2xl", runner)
    tid = eng.submit_task(USER, 0, USER, mid_b, 0, json.dumps(
        {"prompt": "a rocket", "negative_prompt": "", "num_frames": 2,
         "num_inference_steps": 2}).encode())
    drain(node)
    assert node.db.failed_jobs() == []
    sol = eng.solutions[tid]
    assert sol.validator == MINER
    assert len(sol.cid) == 34  # multihash root of the out-1.mp4 dir


def test_rvm_through_node():
    pipe = RVMPipeline(RVMPipelineConfig.tiny())
    params = pipe.init_params(height=32, width=32)
    rng = np.random.default_rng(0)
    src = rng.integers(0, 255, (3, 32, 32, 3)).astype(np.uint8)
    src_mp4 = encode_mp4(src, fps=8)
    store = {"qmInputVideo": src_mp4}
    runner = RVMRunner(pipe, params, resolve_file=store.__getitem__)
    eng, node, mid_b = world("robust_video_matting", runner)
    tid = eng.submit_task(USER, 0, USER, mid_b, 0, json.dumps(
        {"input_video": "qmInputVideo",
         "output_type": "alpha-mask"}).encode())
    drain(node)
    assert node.db.failed_jobs() == []
    assert eng.solutions[tid].validator == MINER


def test_mp4_demux_roundtrip():
    rng = np.random.default_rng(1)
    frames = rng.integers(0, 255, (3, 32, 48, 3)).astype(np.uint8)
    decoded = decode_mjpeg_mp4(encode_mp4(frames, fps=4, quality=95))
    assert decoded.shape == frames.shape
    err = np.abs(decoded.astype(int) - frames.astype(int)).mean()
    assert err < 12.0  # lossy but close; structure is what matters


def test_demux_multi_chunk_layout():
    """stsc-aware: a file with 2 chunks × 2 samples must yield all 4
    frames (regression: zip-truncation dropped all but one per chunk)."""
    import struct

    from arbius_tpu.codecs.jpeg import encode_jpeg
    from arbius_tpu.codecs.mp4 import (_box, _full, _stsd, _mvhd,
                                   _tkhd, _mdhd, _hdlr,
                                   _visual_entry)
    from arbius_tpu.codecs.mp4_demux import demux_mjpeg_mp4

    rng = np.random.default_rng(2)
    jpegs = [encode_jpeg(rng.integers(0, 255, (16, 16, 3)).astype(np.uint8))
             for _ in range(4)]
    ftyp = _box(b"ftyp", b"isom" + struct.pack(">I", 0x200) + b"isomiso2mp41")
    mdat = _box(b"mdat", b"".join(jpegs))
    data_start = len(ftyp) + 8
    chunk2_start = data_start + len(jpegs[0]) + len(jpegs[1])
    stts = _full(b"stts", 0, 0, struct.pack(">III", 1, 4, 1))
    stsc = _full(b"stsc", 0, 0, struct.pack(">IIII", 1, 1, 2, 1))  # 2/chunk
    stsz = _full(b"stsz", 0, 0, struct.pack(">II", 0, 4)
                 + b"".join(struct.pack(">I", len(j)) for j in jpegs))
    stco = _full(b"stco", 0, 0, struct.pack(">III", 2, data_start,
                                            chunk2_start))
    entry = _visual_entry(b"jpeg", 16, 16, b"arbius mjpeg")
    stbl = _box(b"stbl", _stsd(entry) + stts + stsc + stsz + stco)
    dref = _full(b"dref", 0, 0, struct.pack(">I", 1) + _full(b"url ", 0, 1, b""))
    minf = _box(b"minf", _full(b"vmhd", 0, 1, struct.pack(">HHHH", 0, 0, 0, 0))
                + _box(b"dinf", dref) + stbl)
    mdia = _box(b"mdia", _mdhd(4, 4) + _hdlr() + minf)
    trak = _box(b"trak", _tkhd(4, 16, 16) + mdia)
    moov = _box(b"moov", _mvhd(4, 4) + trak)
    samples = demux_mjpeg_mp4(ftyp + mdat + moov)
    assert samples == jpegs


def test_demux_rejects_non_mjpeg():
    with pytest.raises(ValueError):
        decode_mjpeg_mp4(b"\x00\x00\x00\x08ftyp")
